"""Per-request lifecycle traces for the serving request plane.

A `RequestTrace` is a host-side accumulator: the scheduler loop stamps
`time.perf_counter()` phases onto it (queued, prefill, decode chunks,
preempt-requeue, drain-at-swap, shed, finish) as plain list appends —
no locks on the hot path, no device syncs, no allocation beyond the
dicts themselves. At `finish()` the accrued phases flush to the active
`Tracer` in one pass on a synthetic per-request track (tid derived from
the trace id), so a Chrome-trace/Perfetto export shows one lane per
request — the per-phase runtime-timeline discipline of arXiv:1605.08695
applied to requests instead of ops.

Trace context crosses the ND4T wire as a header field (`wire.py`), so a
remote stream through `FleetClient` and the router-side trace share one
trace id and stitch into one timeline.

A sampled-exemplar JSONL sink (`set_exemplar_sink`) persists every Nth
finished trace — enough to answer "show me a slow request" without
writing every request to disk.
"""

from __future__ import annotations

import json
import threading
import time
import uuid
from typing import Dict, List, Optional

from . import tracer as _tracer_mod


def mint_trace_id() -> str:
    return uuid.uuid4().hex[:16]


def _tid_for(trace_id: str) -> int:
    # a stable synthetic track id per trace; keep it positive and well
    # away from real thread idents' low range
    try:
        return (int(trace_id[:8], 16) & 0x7FFFFFFF) | 0x40000000
    except ValueError:
        return (abs(hash(trace_id)) & 0x7FFFFFFF) | 0x40000000


class RequestTrace:
    """Host-side per-request span accumulator.

    All mutators are plain list/dict appends (GIL-atomic, cheap); the
    only costful work — flushing to the Tracer and the exemplar sink —
    happens once, in `finish()`.
    """

    __slots__ = ("trace_id", "parent_id", "remote", "model", "meta",
                 "phases", "events", "status", "t_created", "t_finished",
                 "_finished")

    def __init__(self, trace_id: Optional[str] = None,
                 parent_id: Optional[str] = None, remote: bool = False,
                 model: Optional[str] = None, **meta):
        self.trace_id = trace_id or mint_trace_id()
        self.parent_id = parent_id
        self.remote = bool(remote)
        self.model = model
        self.meta: Dict = dict(meta)
        self.phases: List[Dict] = []
        self.events: List[Dict] = []
        self.status: Optional[str] = None
        self.t_created = time.perf_counter()
        self.t_finished: Optional[float] = None
        self._finished = False

    # ---------------------------------------------------------- recording
    def phase(self, name: str, t0: float, t1: float, **args):
        """One timed phase from two `time.perf_counter()` readings."""
        self.phases.append({"name": name, "t0": t0, "t1": t1,
                            "args": args})

    def event(self, name: str, **args):
        """Zero-duration marker (shed decision, preempt-requeue, ...)."""
        self.events.append({"name": name, "t": time.perf_counter(),
                            "args": args})

    def annotate(self, **meta):
        self.meta.update(meta)

    # ------------------------------------------------------------- finish
    def finish(self, status: str = "ok", **args):
        """Seal the trace: flush phases/events to the active Tracer on a
        per-request track and offer the trace to the exemplar sink.
        Idempotent — a second finish is a no-op."""
        if self._finished:
            return
        self._finished = True
        self.status = status
        self.t_finished = time.perf_counter()
        if args:
            self.meta.update(args)
        self._flush_to_tracer()
        _offer_exemplar(self)

    @property
    def finished(self) -> bool:
        return self._finished

    @property
    def duration_s(self) -> float:
        end = self.t_finished if self.t_finished is not None \
            else time.perf_counter()
        return end - self.t_created

    def _flush_to_tracer(self):
        from . import _STATE  # late: avoid import cycle at module load
        tr = _STATE.tracer
        if tr is None or not tr.enabled:
            return
        tid = _tid_for(self.trace_id)
        label = f"req:{self.trace_id}"
        if self.model:
            label += f" [{self.model}]"
        if self.remote:
            label += " (remote)"
        tr.set_thread_name(tid, label)
        base = {"trace_id": self.trace_id}
        if self.parent_id:
            base["parent_id"] = self.parent_id
        for p in self.phases:
            tr.complete_between(f"req/{p['name']}", p["t0"], p["t1"],
                                tid=tid, **base, **p["args"])
        for e in self.events:
            tr.complete_between(f"req/{e['name']}", e["t"], e["t"],
                                tid=tid, **base, **e["args"])
        tr.complete_between("req/lifetime", self.t_created,
                            self.t_finished, tid=tid,
                            status=self.status, **base, **self.meta)

    # ------------------------------------------------------------- export
    def to_dict(self) -> Dict:
        return {
            "trace_id": self.trace_id,
            "parent_id": self.parent_id,
            "remote": self.remote,
            "model": self.model,
            "status": self.status,
            "t_created": self.t_created,
            "t_finished": self.t_finished,
            "meta": dict(self.meta),
            "phases": [dict(p) for p in self.phases],
            "events": [dict(e) for e in self.events],
        }


# =====================================================================
# sampled-exemplar JSONL sink
# =====================================================================
_sink_lock = threading.Lock()
_sink_path: Optional[str] = None
_sink_every = 16
_sink_seen = 0


def set_exemplar_sink(path: str, sample_every: int = 16):
    """Persist every `sample_every`-th finished trace as one JSONL line.
    `sample_every=1` keeps every trace (smoke tests / debugging)."""
    global _sink_path, _sink_every, _sink_seen
    with _sink_lock:
        _sink_path = path
        _sink_every = max(1, int(sample_every))
        _sink_seen = 0


def clear_exemplar_sink():
    global _sink_path
    with _sink_lock:
        _sink_path = None


def _offer_exemplar(trace: RequestTrace):
    global _sink_seen
    with _sink_lock:
        if _sink_path is None:
            return
        _sink_seen += 1
        if _sink_seen % _sink_every != 0:
            return
        path = _sink_path
    try:
        with open(path, "a") as f:
            f.write(json.dumps(trace.to_dict(), default=str) + "\n")
    except OSError:
        pass  # an unwritable sink must never fail a request


# re-export for callers that want the raw tracer types alongside
Tracer = _tracer_mod.Tracer
