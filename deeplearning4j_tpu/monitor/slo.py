"""SLO objectives + burn-rate tracking for the serving plane.

An `SLOObjective` states per-model latency targets (TTFT and/or TPOT
seconds) and an availability target (e.g. 0.99 = 1% error budget). An
`SLOTracker` classifies each finished request good/bad against the
objective, feeds `slo_requests_good_total` / `slo_requests_bad_total`
counters, and maintains a rolling-window **burn-rate** gauge:

    burn_rate = (bad fraction over the window) / (1 - target)

so 1.0 means "burning budget exactly at the sustainable rate", 10 means
"the whole budget gone in window/10" — the standard multi-window
burn-rate alerting shape. Shed requests count as bad: load shedding is
an availability decision and must spend budget visibly.

Host-side float math only; no device syncs, no JAX imports.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Dict, Optional


class SLOObjective:
    """Per-model latency + availability targets.

    ttft_s / tpot_s: latency thresholds (None = don't judge that axis).
    target: fraction of requests that must be good (0 < target < 1).
    window_s: rolling window the burn rate is computed over.
    """

    __slots__ = ("ttft_s", "tpot_s", "target", "window_s")

    def __init__(self, ttft_s: Optional[float] = None,
                 tpot_s: Optional[float] = None, *,
                 target: float = 0.99, window_s: float = 60.0):
        if not (0.0 < target < 1.0):
            raise ValueError(f"target must be in (0, 1); got {target}")
        if ttft_s is None and tpot_s is None:
            raise ValueError("an SLO needs at least one of ttft_s/tpot_s")
        self.ttft_s = None if ttft_s is None else float(ttft_s)
        self.tpot_s = None if tpot_s is None else float(tpot_s)
        self.target = float(target)
        self.window_s = float(window_s)

    def judge(self, ttft: Optional[float],
              tpot: Optional[float]) -> bool:
        """True = good. A missing measurement on a judged axis (e.g. a
        request shed before first token) is bad."""
        if self.ttft_s is not None:
            if ttft is None or ttft > self.ttft_s:
                return False
        if self.tpot_s is not None:
            # single-token requests have no TPOT; don't judge them on it
            if tpot is not None and tpot > self.tpot_s:
                return False
        return True

    def __repr__(self):
        return (f"SLOObjective(ttft_s={self.ttft_s}, tpot_s={self.tpot_s}, "
                f"target={self.target}, window_s={self.window_s})")


class SLOTracker:
    """Rolling good/bad classifier + burn-rate for one (model, objective).

    `record(ttft=, tpot=)` / `record_shed()` per finished request;
    metric families are passed in pre-resolved by the caller (the
    serving scheduler caches them via `resolve_cached_metrics`), so the
    tracker itself stays registry-agnostic and costs two deque appends
    plus float math per request.
    """

    def __init__(self, objective: SLOObjective,
                 model: Optional[str] = None):
        self.objective = objective
        self.model = model
        self.good_total = 0
        self.bad_total = 0
        self._lock = threading.Lock()
        # (timestamp, good) pairs inside the rolling window
        self._window: deque = deque()

    # ---------------------------------------------------------- recording
    def record(self, ttft: Optional[float] = None,
               tpot: Optional[float] = None,
               now: Optional[float] = None) -> bool:
        """Classify one finished request; returns True if good."""
        good = self.objective.judge(ttft, tpot)
        self._admit(good, now)
        return good

    def record_shed(self, now: Optional[float] = None) -> bool:
        """A shed request spends error budget."""
        self._admit(False, now)
        return False

    def _admit(self, good: bool, now: Optional[float]):
        t = time.monotonic() if now is None else now
        with self._lock:
            if good:
                self.good_total += 1
            else:
                self.bad_total += 1
            self._window.append((t, good))
            self._prune(t)

    def _prune(self, now: float):
        # lock held by caller
        horizon = now - self.objective.window_s
        w = self._window
        while w and w[0][0] < horizon:
            w.popleft()

    # ------------------------------------------------------------ queries
    def burn_rate(self, now: Optional[float] = None) -> float:
        """(bad fraction in window) / error budget. 0.0 when the window
        is empty."""
        t = time.monotonic() if now is None else now
        with self._lock:
            self._prune(t)
            n = len(self._window)
            if n == 0:
                return 0.0
            bad = sum(1 for _, g in self._window if not g)
        return (bad / n) / (1.0 - self.objective.target)

    def window_counts(self, now: Optional[float] = None) -> Dict[str, int]:
        t = time.monotonic() if now is None else now
        with self._lock:
            self._prune(t)
            bad = sum(1 for _, g in self._window if not g)
            return {"good": len(self._window) - bad, "bad": bad}
