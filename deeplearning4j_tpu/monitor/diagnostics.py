"""In-graph model-internals diagnostics: sync-free per-layer training
stats, a device-side non-finite watchdog, and the real feed behind the
training UI / StatsListener.

The reference's signature observability feature is the StatsListener +
training UI (per-layer parameter/gradient/update magnitudes, update:
parameter ratios, activation statistics — `BaseStatsListener.java`
:286-544 and the TrainModule charts). Reproducing it host-side would
mean one device→host sync per param leaf per report and — worse — an
EXTRA eager backward pass just to see gradients, because the real
gradients/updates live inside the fused jitted train step. TensorFlow's
system paper (arXiv:1605.08695) makes the argument this module follows:
training-health introspection must be part of the dataflow program
itself; and arXiv:2606.15870 names silent numeric failure at scale as a
defining resilience constraint — the device-side watchdog below is that
defense.

Design:

- **Stats are auxiliary outputs of the train step.** Both containers
  (and the parallel trainers / gradient-sharing step programs) compute
  per-layer fp32 statistics of the step's REAL gradients, applied
  updates, parameters and activations inside the jitted program and
  return them as one packed f32 vector (`Diagnostics.collect`). The
  trajectory is bit-identical to diagnostics-off — aux outputs only —
  except when the watchdog's explicit ``skip`` policy fires.
- **`stacked::` packed runs stay packed.** Per-layer stats of a run
  are axis-0-preserving reductions over the stacked entry (one [R]
  vector per stat), keyed back to per-layer names at the boundary —
  the same contract checkpoints follow (nn/scan_stack.py): stats are
  independent of the scan configuration.
- **One batched transfer per report.** The packed vector is a single
  device array; `Diagnostics.read` fetches it with ONE `np.asarray`
  (counted on the ``jax_transfers_total{direction="d2h"}`` counter).
  Fused ``steps_per_execution>1`` groups stack per-step vectors in the
  `lax.scan` ys, still one transfer per drain. Off-cadence steps are
  never read — zero additional transfers.
- **Watchdog** (``warn | skip | halt``): per-layer is-finite flags ride
  the stats vector. ``warn`` logs + counts; ``skip`` discards the bad
  update IN-GRAPH (`jnp.where` on the is-finite reduction over the
  step's gradients/updates — params, updater state, exchange residuals
  all keep their previous values) and counts it; ``halt`` raises
  `NonFiniteGradientsError` naming the offending layer keys. Host-side
  actions happen at report cadence (default: every step).

Resolution mirrors ``DL4J_SCAN_LAYERS`` / ``DL4J_DTYPE_POLICY``: the
``DL4J_DIAGNOSTICS`` env override wins (``0/off`` force-disables,
``1/on`` enables the default config, ``warn|skip|halt`` enables with
that watchdog policy), then the container's ``diagnostics=`` argument,
then the configuration's ``diagnostics`` field, then off.
"""

from __future__ import annotations

import dataclasses
import logging
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

log = logging.getLogger("deeplearning4j_tpu")

_ENV_VAR = "DL4J_DIAGNOSTICS"

WATCHDOG_POLICIES = ("warn", "skip", "halt")

# per-(layer, param) statistics in the packed vector
PARAM_STATS = ("grad_mm", "grad_l2", "upd_mm", "upd_l2",
               "param_mm", "param_l2", "ratio")
# per-layer activation statistics
ACT_STATS = ("act_mean", "act_std", "act_dead")


class NonFiniteGradientsError(RuntimeError):
    """Raised by the ``halt`` watchdog policy: the step produced
    non-finite gradients/updates. Carries the offending layer keys and
    the iteration."""

    def __init__(self, layer_keys, iteration):
        self.layer_keys = sorted(str(k) for k in layer_keys)
        self.iteration = int(iteration)
        super().__init__(
            f"non-finite gradients/updates at iteration {iteration} "
            f"(layers {self.layer_keys}) — watchdog policy 'halt'")


@dataclasses.dataclass(frozen=True)
class DiagnosticsConfig:
    """Knobs of the in-graph diagnostics.

    activation_stats: per-layer activation mean/std/dead-unit fraction
        (dead = exactly-zero fraction, the post-ReLU dead-unit signal).
    histograms: fixed-bin in-graph parameter histograms
        (``histogram_bins`` bins over ``[-histogram_range,
        histogram_range]`` — fixed bins keep the program static).
    watchdog: None | "warn" | "skip" | "halt".
    report_frequency: host readback cadence in iterations (the listener
        cadence); the in-graph side always computes (and ``skip``
        always gates) — only VISIBILITY follows the cadence.
    """

    activation_stats: bool = True
    histograms: bool = False
    histogram_bins: int = 20
    histogram_range: float = 1.0
    watchdog: Optional[str] = None
    report_frequency: int = 1

    def __post_init__(self):
        if self.watchdog is not None and self.watchdog not in WATCHDOG_POLICIES:
            raise ValueError(
                f"watchdog must be one of {WATCHDOG_POLICIES} (or None); "
                f"got {self.watchdog!r}")
        if self.report_frequency < 1:
            raise ValueError(
                f"report_frequency must be >= 1, got {self.report_frequency}")
        if self.histogram_bins < 2:
            raise ValueError(
                f"histogram_bins must be >= 2, got {self.histogram_bins}")
        if not self.histogram_range > 0:
            raise ValueError(
                f"histogram_range must be > 0, got {self.histogram_range}")

    # ----------------------------------------------------------- serde
    def to_dict(self) -> dict:
        return {
            "activation_stats": self.activation_stats,
            "histograms": self.histograms,
            "histogram_bins": self.histogram_bins,
            "histogram_range": self.histogram_range,
            "watchdog": self.watchdog,
            "report_frequency": self.report_frequency,
        }

    @staticmethod
    def from_dict(d: dict) -> "DiagnosticsConfig":
        return DiagnosticsConfig(
            activation_stats=bool(d.get("activation_stats", True)),
            histograms=bool(d.get("histograms", False)),
            histogram_bins=int(d.get("histogram_bins", 20)),
            histogram_range=float(d.get("histogram_range", 1.0)),
            watchdog=d.get("watchdog"),
            report_frequency=int(d.get("report_frequency", 1)),
        )


def as_diagnostics(spec) -> Optional[DiagnosticsConfig]:
    """Coerce a user-facing spec to a DiagnosticsConfig (or None):
    None/False → off, True/"on"/"default" → defaults, a watchdog policy
    name → defaults with that policy, a dict → serde form, a config →
    itself."""
    if spec is None or spec is False:
        return None
    if spec is True:
        return DiagnosticsConfig()
    if isinstance(spec, DiagnosticsConfig):
        return spec
    if isinstance(spec, str):
        v = spec.strip().lower()
        if v in ("1", "on", "true", "yes", "default"):
            return DiagnosticsConfig()
        if v in ("0", "off", "false", "no"):
            return None
        if v in WATCHDOG_POLICIES:
            return DiagnosticsConfig(watchdog=v)
        raise ValueError(
            f"cannot interpret {spec!r} as a diagnostics spec; known "
            f"names: on/off/default or a watchdog policy "
            f"{WATCHDOG_POLICIES}")
    if isinstance(spec, dict):
        return DiagnosticsConfig.from_dict(spec)
    raise TypeError(f"cannot interpret {spec!r} as a diagnostics spec")


_ENV_OFF = object()  # sentinel: env explicitly forces diagnostics OFF


def env_diagnostics():
    """The ``DL4J_DIAGNOSTICS`` override: None when unset, the `_ENV_OFF`
    sentinel when explicitly disabled, else a DiagnosticsConfig.
    Unknown spellings raise (a typo'd fleet A/B toggle must not
    silently no-op)."""
    import os
    env = os.environ.get(_ENV_VAR)
    if env is None or not env.strip():
        return None
    v = env.strip().lower()
    if v in ("0", "off", "false", "no"):
        return _ENV_OFF
    if v in ("1", "on", "true", "yes"):
        return DiagnosticsConfig()
    if v in WATCHDOG_POLICIES:
        return DiagnosticsConfig(watchdog=v)
    raise ValueError(
        f"{_ENV_VAR}={env!r}: expected 0/off/1/on or a watchdog policy "
        f"{WATCHDOG_POLICIES}")


def resolve_diagnostics(explicit=None, conf=None) -> Optional[DiagnosticsConfig]:
    """Container-side resolution: DL4J_DIAGNOSTICS env override wins
    (including force-off), then the explicit constructor argument, then
    the configuration's ``diagnostics`` field, then off."""
    forced = env_diagnostics()
    if forced is _ENV_OFF:
        return None
    if forced is not None:
        return forced
    e = as_diagnostics(explicit)
    if e is not None:
        return e
    return as_diagnostics(getattr(conf, "diagnostics", None))


# ------------------------------------------------------- in-graph helpers
def _f32(a):
    a = jnp.asarray(a)
    return a if a.dtype == jnp.float32 else a.astype(jnp.float32)


def activation_stats(h):
    """[mean, std, dead-fraction] of one layer's output, computed fp32
    regardless of the activation dtype (the mixed_bf16 rule: statistics
    never accumulate in bf16)."""
    h32 = _f32(h)
    return jnp.stack([jnp.mean(h32), jnp.std(h32),
                      jnp.mean((h32 == 0).astype(jnp.float32))])


def keep_finite(ok, new_tree, old_tree):
    """The watchdog ``skip`` gate: elementwise select on the step-global
    is-finite flag — when the step was finite the select returns the
    new values BITWISE, so enabling the watchdog never perturbs a
    healthy trajectory."""
    return jax.tree_util.tree_map(
        lambda n, o: jnp.where(ok, n, o.astype(n.dtype)), new_tree, old_tree)


def _members_of(lk: str) -> List[str]:
    from deeplearning4j_tpu.nn import scan_stack
    if scan_stack.is_run_key(lk):
        return scan_stack.run_members(lk)
    return [lk]


def _reduce_axes(leaf, n_members: int):
    """Reduction axes keeping a packed run's leading layer axis (the
    "per-layer stats without unpacking" contract): all axes for a
    singleton, axes 1.. for a stacked entry."""
    if n_members == 1:
        return None
    return tuple(range(1, jnp.ndim(leaf))) or None


def _as_members(v, n_members: int):
    """A stat value as a list of per-member scalars."""
    if n_members == 1:
        return [v]
    return [v[j] for j in range(n_members)]


class Diagnostics:
    """Per-model diagnostics engine: trace-time stat packing + host-side
    readback/watchdog, sharing one DiagnosticsConfig.

    Layouts (the static key list describing the packed vector) are kept
    per program family (``name``): the containers' fit step ("fit"),
    the gradient-sharing exchange step ("exchange" — update/param stats
    only; raw grads live inside the VJP hooks there), the pipeline
    trainer ("pipeline"). A layout is established the first time the
    matching program traces `collect` and reused by every `read`."""

    def __init__(self, config: DiagnosticsConfig):
        self.config = config
        self.layouts: Dict[str, List[Tuple[str, int]]] = {}
        self.nonfinite_total = 0
        self.skipped_total = 0
        self.last: Optional[dict] = None

    # ------------------------------------------------------- trace time
    def collect(self, name: str, *, params_new, params_old, loss,
                grads=None, acts=None, extra_finite=None, axis_name=None):
        """Build the packed diag vector for ONE step (called at trace
        time inside the jitted step). Trees may contain ``stacked::``
        run entries — never unpacked; per-layer stats use axis-0-
        preserving reductions.

        grads: post-normalization gradient tree (None on the exchange
        paths, where gradients live inside the VJP hooks — update stats
        are post-exchange by construction there).
        acts: {tree_key: [3] or [R, 3]} activation stats.
        extra_finite: additional tree (e.g. the error-feedback residual)
        folded into the per-layer finite flags.
        axis_name: shard_map data axis — per-replica non-finite counts
        are psum'd so the flags (and the skip gate) are global.

        Returns (dv, ok): dv is ``{"v": flat f32 vector}``, ok the
        step-global is-finite bool (the ``skip`` gate input)."""
        cfg = self.config
        entries: Dict[str, Any] = {}
        layer_bad: Dict[str, Any] = {}

        def add_bad(mk, v):
            layer_bad[mk] = layer_bad.get(mk, jnp.float32(0.0)) + v

        for lk in params_new:
            members = _members_of(lk)
            R = len(members)
            for pn in params_new[lk]:
                p_new = _f32(params_new[lk][pn])
                p_old = _f32(params_old[lk][pn])
                axes = _reduce_axes(p_new, R)
                upd = p_old - p_new
                stats = {
                    "param_mm": jnp.mean(jnp.abs(p_new), axis=axes),
                    "param_l2": jnp.sqrt(jnp.sum(p_new * p_new, axis=axes)),
                    "upd_mm": jnp.mean(jnp.abs(upd), axis=axes),
                    "upd_l2": jnp.sqrt(jnp.sum(upd * upd, axis=axes)),
                }
                stats["ratio"] = stats["upd_mm"] / (stats["param_mm"] + 1e-12)
                # finite flags watch the UPDATE as well as the gradient:
                # an inf learning rate (or poisoned updater state) turns
                # finite gradients into a non-finite update — the skip
                # gate must fire on either
                bad = jnp.sum((~jnp.isfinite(upd)).astype(jnp.float32),
                              axis=axes)
                if grads is not None:
                    g = _f32(grads[lk][pn])
                    stats["grad_mm"] = jnp.mean(jnp.abs(g), axis=axes)
                    stats["grad_l2"] = jnp.sqrt(jnp.sum(g * g, axis=axes))
                    bad = bad + jnp.sum(
                        (~jnp.isfinite(g)).astype(jnp.float32), axis=axes)
                if extra_finite is not None and pn in extra_finite.get(lk, {}):
                    e = _f32(extra_finite[lk][pn])
                    bad = bad + jnp.sum(
                        (~jnp.isfinite(e)).astype(jnp.float32), axis=axes)
                for st, v in stats.items():
                    for j, mk in enumerate(_as_members(v, R)):
                        entries[f"{st}.{members[j]}_{pn}"] = mk
                for j, b in enumerate(_as_members(bad, R)):
                    add_bad(members[j], b)
                if cfg.histograms:
                    lo, hi = -cfg.histogram_range, cfg.histogram_range

                    def hist(a):
                        c, _ = jnp.histogram(
                            jnp.reshape(a, (-1,)), bins=cfg.histogram_bins,
                            range=(lo, hi))
                        return c.astype(jnp.float32)

                    if R == 1:
                        hs = [hist(p_new)]
                    else:
                        hs = list(jax.vmap(hist)(p_new))
                    for j, hv in enumerate(hs):
                        entries[f"hist.{members[j]}_{pn}"] = hv

        if acts:
            for lk, sv in acts.items():
                members = _members_of(lk)
                sv = _f32(sv)
                for j, mk in enumerate(members):
                    row = sv if len(members) == 1 and sv.ndim == 1 else sv[j]
                    for si, st in enumerate(ACT_STATS):
                        entries[f"{st}.{mk}"] = row[si]

        total_bad = jnp.float32(0.0)
        for mk in layer_bad:
            total_bad = total_bad + layer_bad[mk]
        loss_bad = (~jnp.isfinite(_f32(loss))).astype(jnp.float32)
        if jnp.ndim(loss_bad):
            loss_bad = jnp.sum(loss_bad)
        total_bad = total_bad + loss_bad
        if axis_name is not None:
            # per-replica counts → global flags (one tiny psum; the
            # skip gate must fire on EVERY replica or params diverge)
            stacked_bad = jnp.stack(
                [layer_bad[mk] for mk in sorted(layer_bad)] + [total_bad])
            stacked_bad = jax.lax.psum(stacked_bad, axis_name)
            for i, mk in enumerate(sorted(layer_bad)):
                layer_bad[mk] = stacked_bad[i]
            total_bad = stacked_bad[-1]
        for mk, b in layer_bad.items():
            entries[f"finite.{mk}"] = (b == 0).astype(jnp.float32)
        # the loss can be the only non-finite value (saturated logits
        # can yield a NaN loss with finite gradients) — flag it under
        # its own key so halt/warn name SOMETHING
        entries["finite.<loss>"] = (loss_bad == 0).astype(jnp.float32)
        entries["nonfinite"] = (total_bad > 0).astype(jnp.float32)
        ok = total_bad == 0

        keys = sorted(entries)
        layout: List[Tuple[str, int]] = []
        pieces = []
        for k in keys:
            v = jnp.reshape(_f32(entries[k]), (-1,))
            layout.append((k, int(v.shape[0])))
            pieces.append(v)
        self.layouts[name] = layout
        vec = jnp.concatenate(pieces) if pieces \
            else jnp.zeros((0,), jnp.float32)
        return {"v": vec}, ok

    # --------------------------------------------------------- host side
    def due(self, iteration: int) -> bool:
        return iteration % self.config.report_frequency == 0

    def read(self, dv, name: str) -> List[dict]:
        """ONE batched device→host transfer of the packed vector (or the
        fused group's [k, K] stack), sliced by the layout into one
        structured dict per step."""
        from deeplearning4j_tpu import monitor
        vec = np.asarray(dv["v"])
        monitor.record_transfer(vec.nbytes, "d2h")
        rows = vec if vec.ndim == 2 else vec[None]
        layout = self.layouts[name]
        out = []
        for row in rows:
            flat = {}
            off = 0
            for k, size in layout:
                flat[k] = (float(row[off]) if size == 1
                           else np.array(row[off:off + size]))
                off += size
            out.append(self._structure(flat))
        return out

    @staticmethod
    def _structure(flat: dict) -> dict:
        d = {"params": {}, "activations": {}, "hists": {}, "finite": {},
             "nonfinite": bool(flat.get("nonfinite", 0.0))}
        for k, v in flat.items():
            if "." not in k:
                continue
            st, key = k.split(".", 1)
            if st in PARAM_STATS:
                d["params"].setdefault(key, {})[st] = v
            elif st in ACT_STATS:
                short = {"act_mean": "mean", "act_std": "std",
                         "act_dead": "dead"}[st]
                d["activations"].setdefault(key, {})[short] = v
            elif st == "hist":
                d["hists"][key] = v
            elif st == "finite":
                d["finite"][key] = bool(v)
        return d

    def process(self, model, dv, name: str, it0: int) -> List[dict]:
        """Read one step's (or one fused group's) diag vector, apply the
        watchdog's host-side actions, publish registry gauges, and cache
        the latest host stats on the model (``model._last_diagnostics``
        — what StatsListener / ParamAndGradientIterationListener
        consume). Raises NonFiniteGradientsError under ``halt``."""
        if not dv:
            return []
        rows = self.read(dv, name)
        policy = self.config.watchdog
        from deeplearning4j_tpu import monitor
        mon = monitor.is_enabled()
        reg = monitor.registry() if mon else None
        for i, row in enumerate(rows):
            if not row["nonfinite"]:
                continue
            bad = [k for k, fine in row["finite"].items() if not fine]
            self.nonfinite_total += 1
            if mon:
                reg.counter(
                    "watchdog_nonfinite_total",
                    help="steps that produced non-finite grads/updates",
                ).inc()
                reg.gauge("watchdog_last_nonfinite_iteration",
                          help="iteration of the last non-finite step",
                          ).set(float(it0 + i))
            if policy == "skip":
                self.skipped_total += 1
                if mon:
                    reg.counter(
                        "watchdog_skipped_total",
                        help="updates discarded in-graph by the skip "
                             "policy").inc()
                log.warning(
                    "diagnostics watchdog: non-finite update at iteration "
                    "%d (layers %s) — update SKIPPED in-graph",
                    it0 + i, sorted(bad))
            elif policy == "halt":
                from deeplearning4j_tpu.monitor.flightrec import (
                    GLOBAL_FLIGHT_RECORDER,
                )
                GLOBAL_FLIGHT_RECORDER.record(
                    "watchdog_halt", layers=sorted(bad),
                    iteration=int(it0 + i))
                raise NonFiniteGradientsError(bad, it0 + i)
            else:  # warn (and None: count only)
                if policy == "warn":
                    log.warning(
                        "diagnostics watchdog: non-finite gradients/"
                        "updates at iteration %d (layers %s)",
                        it0 + i, sorted(bad))
        last = rows[-1]
        if mon:
            for key, st in last["params"].items():
                if "grad_l2" in st:
                    reg.gauge("training_grad_l2",
                              help="per-param gradient L2 norm",
                              param=key).set(st["grad_l2"])
                reg.gauge("training_update_l2",
                          help="per-param applied-update L2 norm",
                          param=key).set(st["upd_l2"])
                reg.gauge("training_update_ratio",
                          help="mean |update| : mean |param| ratio",
                          param=key).set(st["ratio"])
            for lk, st in last["activations"].items():
                reg.gauge("training_activation_std",
                          help="per-layer activation std",
                          layer=lk).set(st["std"])
                reg.gauge("training_activation_dead",
                          help="per-layer exactly-zero activation "
                               "fraction", layer=lk).set(st["dead"])
        self.last = last
        model._last_diagnostics = last
        return rows


def collect_and_gate(diag, name: str, *, params_old, params_new, upd_old,
                     upd_new, state_old, state_new, grads, loss,
                     acts=None):
    """The containers' shared diagnostics tail: collect the step's
    stats and, under the ``skip`` watchdog, discard the bad update
    in-graph (params/updater/layer state keep their previous values).
    One copy for the per-step, fused-scan and pipeline step bodies —
    the gradient-sharing cores have their own (`_exchange_diag`, which
    additionally reverts residual/τ). Returns
    (params_new, upd_new, state_new, dv)."""
    if diag is None:
        return params_new, upd_new, state_new, {}
    dv, ok = diag.collect(name, params_new=params_new,
                          params_old=params_old, grads=grads, loss=loss,
                          acts=acts)
    if diag.config.watchdog == "skip":
        params_new = keep_finite(ok, params_new, params_old)
        upd_new = keep_finite(ok, upd_new, upd_old)
        state_new = {k: (keep_finite(ok, v, state_old[k])
                         if k in state_old else v)
                     for k, v in state_new.items()}
    return params_new, upd_new, state_new, dv


def process_if_due(model, dv, name: str, it0: int, steps: int = 1):
    """Trainer-side cadence gate: process the step's (or fused group's)
    diag vector iff the model has diagnostics AND any covered iteration
    is on report cadence. Returns the host-stat rows or None — callers
    hand ``rows[j]`` to on-cadence listener callbacks. Off-cadence:
    nothing is read, zero transfers."""
    md = getattr(model, "_diag", None)
    if md is None or not dv:
        return None
    if not any(md.due(it0 + j) for j in range(steps)):
        return None
    return md.process(model, dv, name, it0)


# ----------------------------------------------- batched host readback
_BATCH_FETCH_CACHE: Dict[Any, Any] = {}


def batched_host_tree(tree):
    """Fetch every leaf of a device tree to host numpy in ONE batched
    device→host transfer: a tiny jitted program concatenates the
    raveled f32 leaves into one buffer, fetched with a single
    `np.asarray` (counted as one d2h transfer). Host-resident trees
    (numpy leaves) pass through with zero transfers.

    This is the StatsListener seam: the reference behavior (one
    `np.asarray` per param leaf per report) cost one device round-trip
    per leaf."""
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    if not leaves:
        return tree
    if all(isinstance(l, np.ndarray) for l in leaves):
        return tree
    from deeplearning4j_tpu import monitor
    key = (treedef, tuple((tuple(np.shape(l)), str(getattr(l, "dtype", "?")))
                          for l in leaves))
    fn = _BATCH_FETCH_CACHE.get(key)
    if fn is None:
        def concat(ls):
            return jnp.concatenate([jnp.reshape(_f32(l), (-1,))
                                    for l in ls])
        fn = jax.jit(concat)
        if len(_BATCH_FETCH_CACHE) > 64:
            _BATCH_FETCH_CACHE.clear()
        _BATCH_FETCH_CACHE[key] = fn
    flat = np.asarray(fn(leaves))
    monitor.record_transfer(flat.nbytes, "d2h")
    out = []
    off = 0
    for l in leaves:
        n = int(np.prod(np.shape(l)))
        out.append(flat[off:off + n].reshape(np.shape(l)))
        off += n
    return jax.tree_util.tree_unflatten(treedef, out)
