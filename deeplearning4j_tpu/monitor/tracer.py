"""Nested-span tracer with monotonic clocks + Chrome trace export.

The per-phase timeline half of the telemetry substrate (the discipline
TensorFlow's runtime tracing established, arXiv:1605.08695): spans nest
per-thread, timestamps come from `time.perf_counter_ns()` (monotonic —
NTP steps can't produce negative durations), and the whole buffer
exports as Chrome trace-event JSON that loads directly in Perfetto
(`ui.perfetto.dev`) next to the XLA traces ProfilerListener captures.

Pure stdlib, bounded memory (ring buffer), thread-safe.
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from typing import Dict, List, Optional


class Span:
    __slots__ = ("name", "start_ns", "end_ns", "args", "thread_id", "_tracer")

    def __init__(self, tracer: "Tracer", name: str, args: Dict):
        self._tracer = tracer
        self.name = name
        self.args = args
        self.thread_id = threading.get_ident()
        self.start_ns = 0
        self.end_ns = 0

    @property
    def duration_ms(self) -> float:
        return (self.end_ns - self.start_ns) / 1e6

    def set(self, **args):
        self.args.update(args)
        return self

    def __enter__(self) -> "Span":
        self.start_ns = time.perf_counter_ns()
        return self

    def __exit__(self, exc_type, exc, tb):
        self.end_ns = time.perf_counter_ns()
        if exc_type is not None:
            self.args["error"] = exc_type.__name__
        self._tracer._commit(self)
        return False


class _NoopSpan:
    """Shared do-nothing span — what a disabled tracer hands out, so hot
    paths stay allocation-free when monitoring is off."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def set(self, **args):
        return self


NOOP_SPAN = _NoopSpan()


class Tracer:
    """Ring-buffered span recorder.

    `with tracer.span("fit/forward_backward", iteration=i): ...` records
    one complete event; nesting is positional (Perfetto reconstructs the
    stack from enclosing timestamps per thread, Chrome "X" events).
    """

    def __init__(self, max_events: int = 200_000, enabled: bool = True):
        self.enabled = enabled
        self._lock = threading.Lock()
        self._events: deque = deque(maxlen=max_events)
        self._origin_ns = time.perf_counter_ns()
        self._pid = os.getpid()
        #: spans lost to ring-buffer overflow — the deque drops the
        #: OLDEST event silently, so exports must say how much history
        #: is missing or a truncated trace reads as a complete one
        self.events_dropped = 0
        # optional registry counter wired by monitor.enable()
        self._drop_counter = None

    def _note_drop(self):
        # lock held by caller; the registry RLock is taken INSIDE the
        # tracer lock (safe: the registry never calls into the tracer)
        if len(self._events) == self._events.maxlen:
            self.events_dropped += 1
            c = self._drop_counter
            if c is not None:
                c.inc()

    # ---------------------------------------------------------- recording
    def span(self, name: str, **args) -> Span:
        if not self.enabled:
            return NOOP_SPAN
        return Span(self, name, args)

    def _commit(self, span: Span):
        with self._lock:
            self._note_drop()
            self._events.append({
                "name": span.name,
                "ph": "X",
                "ts": (span.start_ns - self._origin_ns) / 1e3,  # µs
                "dur": (span.end_ns - span.start_ns) / 1e3,
                "pid": self._pid,
                "tid": span.thread_id,
                "args": span.args,
            })

    def add_complete_event(self, name: str, start_s: float, duration_s: float,
                           **args):
        """Record a span whose window was timed externally (e.g. a
        TrainingMasterStats phase event) — start_s is seconds since an
        arbitrary epoch consistent within the caller."""
        if not self.enabled:
            return
        with self._lock:
            self._note_drop()
            self._events.append({
                "name": name, "ph": "X",
                "ts": start_s * 1e6, "dur": duration_s * 1e6,
                "pid": self._pid, "tid": threading.get_ident(),
                "args": args,
            })

    def complete_between(self, name: str, t0_perf: float, t1_perf: float,
                         tid: Optional[int] = None, **args):
        """Record a span from two `time.perf_counter()` readings (same
        monotonic clock as the tracer origin), e.g. an ETL window the
        iterator timed itself. `tid` overrides the track id — request
        traces use one synthetic track per request so Perfetto renders
        each request's lifecycle as its own lane."""
        if not self.enabled:
            return
        start_ns = int(t0_perf * 1e9) - self._origin_ns
        with self._lock:
            self._note_drop()
            self._events.append({
                "name": name, "ph": "X",
                "ts": start_ns / 1e3,
                "dur": max(0.0, (t1_perf - t0_perf) * 1e6),
                "pid": self._pid,
                "tid": threading.get_ident() if tid is None else int(tid),
                "args": args,
            })

    def instant(self, name: str, tid: Optional[int] = None, **args):
        """Zero-duration marker (Chrome 'i' event)."""
        if not self.enabled:
            return
        with self._lock:
            self._note_drop()
            self._events.append({
                "name": name, "ph": "i", "s": "t",
                "ts": (time.perf_counter_ns() - self._origin_ns) / 1e3,
                "pid": self._pid,
                "tid": threading.get_ident() if tid is None else int(tid),
                "args": args,
            })

    def set_thread_name(self, tid: int, name: str):
        """Label a track (Chrome 'M' thread_name metadata event) — how a
        synthetic per-request track gets its trace id as the lane name."""
        if not self.enabled:
            return
        with self._lock:
            self._note_drop()
            self._events.append({
                "name": "thread_name", "ph": "M",
                "pid": self._pid, "tid": int(tid),
                "args": {"name": name},
            })

    # ------------------------------------------------------------ queries
    def events(self) -> List[Dict]:
        with self._lock:
            return list(self._events)

    def span_names(self) -> Dict[str, int]:
        out: Dict[str, int] = {}
        for ev in self.events():
            if ev["ph"] == "X":
                out[ev["name"]] = out.get(ev["name"], 0) + 1
        return out

    def clear(self):
        with self._lock:
            self._events.clear()
            self._origin_ns = time.perf_counter_ns()
            self.events_dropped = 0

    # ------------------------------------------------------------- export
    def export_chrome_trace(self, path: Optional[str] = None) -> str:
        """Chrome trace-event JSON (object form). Loadable in Perfetto
        and `chrome://tracing`; returns the JSON string, optionally also
        writing it to `path`."""
        doc = {
            "traceEvents": self.events(),
            "displayTimeUnit": "ms",
            "otherData": {"exporter": "deeplearning4j_tpu.monitor",
                          "events_dropped": self.events_dropped},
        }
        text = json.dumps(doc)
        if path is not None:
            with open(path, "w") as f:
                f.write(text)
        return text

    def export_jsonl(self, path: str) -> str:
        """One event per line — the append-friendly event-log sink."""
        with open(path, "a") as f:
            for ev in self.events():
                f.write(json.dumps({"kind": "span", **ev}) + "\n")
        return path


GLOBAL_TRACER = Tracer(enabled=False)
