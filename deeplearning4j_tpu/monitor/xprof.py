"""Compile-time / profiler observability: the tunnel-independent half
of the telemetry core.

Runtime telemetry (registry + tracer + collectors) needs a live
process doing work; everything in this module works with **no
accelerator attached**, because it operates at the compiled-program
level — the design point both TensorFlow's whole-dataflow-graph cost
model (arXiv:1605.08695 §3.2.1) and the Julia→TPU AOT pipeline
(arXiv:1810.09868) argue for: analyze the program XLA will run, not
the silicon you may not have.

Three pieces:

- `roofline()` — the classic two-ceiling model (arithmetic intensity
  vs a compute peak and a memory-bandwidth peak) that turns an AOT
  cost analysis (total FLOPs + bytes accessed) into a predicted step
  time and a predicted MFU. Pure math, unit-tested.
- cost-report registry — `publish_cost_report()` stores the JSON
  artifacts `benchtools/hlo_cost.py` emits (``PROFILE_*/cost_*.json``)
  and mirrors the headline figures onto the metrics registry as
  ``aot_cost_*`` gauges; `cost_reports(scan=True)` is what the
  UIServer's ``/profile`` route renders (falling back to scanning the
  working directory for committed artifacts).
- `ProfilerCapture` — the programmatic `jax.profiler` seam: start/stop
  an xplane trace around fit-loop spans from driver code (what
  `scripts/tunnel_window.sh` uses so one command turns a live tunnel
  window into a committed trace). Works on CPU too (host plane only).
"""

from __future__ import annotations

import glob
import json
import os
import threading
import time
from typing import Dict, Optional

__all__ = [
    "ProfilerCapture", "roofline", "publish_cost_report",
    "cost_reports", "clear_cost_reports", "load_cost_reports",
]


# ---------------------------------------------------------------- roofline
def roofline(flops: float, bytes_accessed: float, peak_flops: float,
             peak_bytes_per_sec: float) -> Dict[str, float]:
    """Two-ceiling roofline for one training step.

    `peak_flops` should be the *measured* matmul ceiling where one
    exists (bench.py's speed-of-light probe — what the silicon
    demonstrably sustains), not the datasheet number: a predicted MFU
    against an unreachable peak is not falsifiable.

    Returns arithmetic intensity (FLOP/byte), the critical intensity
    where the ceilings cross, which ceiling binds, per-ceiling step
    times, and the predicted step time / throughput / MFU at the
    binding ceiling. `bytes_accessed` from unoptimized HLO overstates
    traffic (fusion elides intermediates), so the memory ceiling is an
    upper bound on step time and `predicted_mfu` a lower bound —
    callers should report `mfu_if_compute_bound` alongside it.
    """
    flops = float(flops)
    bytes_accessed = float(bytes_accessed)
    if flops <= 0 or peak_flops <= 0 or peak_bytes_per_sec <= 0:
        raise ValueError("roofline needs positive flops and peaks")
    ai = flops / max(bytes_accessed, 1.0)
    critical_ai = peak_flops / peak_bytes_per_sec
    t_compute = flops / peak_flops
    t_memory = bytes_accessed / peak_bytes_per_sec
    t = max(t_compute, t_memory)
    return {
        "arithmetic_intensity_flop_per_byte": ai,
        "critical_intensity_flop_per_byte": critical_ai,
        "bound": "compute" if t_compute >= t_memory else "memory",
        "step_seconds_compute_bound": t_compute,
        "step_seconds_memory_bound": t_memory,
        "predicted_step_seconds": t,
        "predicted_flops_per_sec": flops / t,
        "predicted_mfu": (flops / t) / peak_flops,
        "mfu_if_compute_bound": 1.0,
    }


# ------------------------------------------------------ cost-report store
_REPORTS: Dict[str, dict] = {}
_REPORTS_LOCK = threading.Lock()

_GAUGE_FIELDS = (
    # (gauge name, report path) — headline figures mirrored to /metrics
    ("aot_cost_flops_per_step", ("per_op", "total_flops_per_step")),
    ("aot_cost_bytes_per_step", ("per_op", "total_bytes_per_step")),
    ("aot_cost_arithmetic_intensity",
     ("roofline", "arithmetic_intensity_flop_per_byte")),
    ("aot_cost_predicted_step_seconds", ("roofline", "predicted_step_seconds")),
    ("aot_cost_predicted_mfu", ("predicted", "mfu")),
    # program section (scan-over-layers observability): how big the
    # compiled train step is and what compiling it cost
    ("aot_compile_seconds", ("program", "compile_seconds")),
    ("aot_compile_jaxpr_eqns", ("program", "jaxpr_eqn_count")),
    ("aot_compile_peak_temp_bytes", ("program", "peak_temp_bytes")),
    ("aot_compile_code_size_bytes",
     ("program", "generated_code_size_in_bytes")),
    # gradient-exchange payload (threshold-encoded gradient sharing —
    # parallel/gradient_sharing.py wire format vs dense fp32)
    ("aot_comm_bytes_dense", ("program", "comm_bytes",
                              "dense_bytes_per_step")),
    ("aot_comm_bytes_threshold", ("program", "comm_bytes",
                                  "threshold_bytes_per_step")),
    ("aot_comm_bytes_reduction", ("program", "comm_bytes", "reduction")),
    # exposed-vs-overlapped comm bytes of the bucketed exchange
    # (benchtools/hlo_cost.comm_overlap_block; headline = the sync
    # trainers' default bucketed-dense program)
    ("aot_comm_overlap_exposed_bytes", ("program", "comm_overlap",
                                        "exposed_bytes")),
    ("aot_comm_overlap_overlapped_bytes", ("program", "comm_overlap",
                                           "overlapped_bytes")),
    ("aot_comm_overlap_exposed_fraction", ("program", "comm_overlap",
                                           "exposed_fraction")),
    # dtype-policy (mixed-precision) evidence — fp32-vs-bf16 bytes per
    # step of the SAME program (benchtools/hlo_cost.precision_block)
    ("aot_precision_fp32_bytes_per_step", ("precision", "float32",
                                           "bytes_per_step")),
    ("aot_precision_bf16_bytes_per_step", ("precision", "mixed_bf16",
                                           "bytes_per_step")),
    ("aot_precision_bytes_reduction", ("precision", "bytes_reduction")),
    ("aot_precision_wire_reduction", ("precision", "wire_reduction")),
)


def _dig(d, path):
    for p in path:
        if not isinstance(d, dict):
            return None
        d = d.get(p)
    return d


def publish_cost_report(report: dict, registry=None) -> dict:
    """Store one cost report (keyed by its ``model`` field) for the
    ``/profile`` route and mirror its headline numbers onto the metrics
    registry as ``aot_cost_*{model=...}`` gauges. `registry=None` uses
    the monitor's active registry. Returns the report."""
    model = str(report.get("model", "unknown"))
    with _REPORTS_LOCK:
        _REPORTS[model] = report
    if registry is None:
        from deeplearning4j_tpu import monitor
        registry = monitor.registry()
    for gname, path in _GAUGE_FIELDS:
        val = _dig(report, path)
        if isinstance(val, (int, float)):
            registry.gauge(
                gname, help="AOT HLO cost analysis (benchtools/hlo_cost.py)",
                model=model).set(float(val))
    return report


def clear_cost_reports():
    with _REPORTS_LOCK:
        _REPORTS.clear()


def load_cost_reports(root: str = ".") -> Dict[str, dict]:
    """Scan committed artifacts (``PROFILE_*/cost_*.json`` under
    `root`) — lets a UI-only process serve /profile from the repo's
    checked-in cost tables without re-running the analysis."""
    out: Dict[str, dict] = {}
    for path in sorted(glob.glob(os.path.join(root, "PROFILE_*",
                                              "cost_*.json"))):
        try:
            with open(path) as f:
                rep = json.load(f)
        except (OSError, ValueError):
            continue
        if isinstance(rep, dict):
            out[str(rep.get("model",
                            os.path.basename(path)[5:-5] or path))] = rep
    return out


def cost_reports(scan: bool = False, root: str = ".") -> Dict[str, dict]:
    """Reports published in-process; with `scan=True`, disk artifacts
    fill in models nothing has published yet (published wins)."""
    with _REPORTS_LOCK:
        published = dict(_REPORTS)
    if not scan:
        return published
    merged = load_cost_reports(root)
    merged.update(published)
    return merged


# ------------------------------------------------------- profiler capture
class ProfilerCapture:
    """Programmatic `jax.profiler` trace seam.

    The ProfilerListener (optimize/listeners.py) picks iterations from
    inside a fit loop; this seam is for *driver* code that brackets an
    arbitrary window — a whole bench run, one fused dispatch, a sweep —
    so the next live tunnel window yields an xplane trace with one
    command (`scripts/tunnel_window.sh`)::

        from deeplearning4j_tpu.monitor import ProfilerCapture
        with ProfilerCapture("PROFILE_live/trace"):
            bench.bench_resnet50(accel=True)

    start()/stop() may also be called explicitly (stop() is idempotent
    and returns the logdir, or None if nothing was active). Captures
    record `profiler_captures_total` / `profiler_capture_seconds` on
    the monitor registry when monitoring is enabled, and a
    `profiler/capture` span on the tracer — so capture windows are
    visible on the same timeline as the fit spans they wrap."""

    def __init__(self, logdir: str, *, host_tracer_level: int = 2,
                 python_tracer_level: int = 0):
        self.logdir = str(logdir)
        self.host_tracer_level = host_tracer_level
        self.python_tracer_level = python_tracer_level
        self.active = False
        self._t0: Optional[float] = None
        self._span = None

    def start(self) -> "ProfilerCapture":
        if self.active:
            raise RuntimeError(
                f"ProfilerCapture already active (logdir={self.logdir})")
        import jax
        os.makedirs(self.logdir, exist_ok=True)
        try:
            options = jax.profiler.ProfileOptions()
            options.host_tracer_level = self.host_tracer_level
            options.python_tracer_level = self.python_tracer_level
            jax.profiler.start_trace(self.logdir, profiler_options=options)
        except (TypeError, AttributeError):
            # older jax: no ProfileOptions plumbing — default levels
            jax.profiler.start_trace(self.logdir)
        self.active = True
        self._t0 = time.perf_counter()
        from deeplearning4j_tpu import monitor
        if monitor.is_enabled():
            monitor.registry().counter(
                "profiler_captures_total",
                help="xplane capture windows started").inc()
            self._span = monitor.span("profiler/capture", logdir=self.logdir)
            self._span.__enter__()
        return self

    def stop(self) -> Optional[str]:
        if not self.active:
            return None
        import jax
        try:
            jax.profiler.stop_trace()
        finally:
            self.active = False
        dur = time.perf_counter() - (self._t0 or time.perf_counter())
        if self._span is not None:
            self._span.__exit__(None, None, None)
            self._span = None
        from deeplearning4j_tpu import monitor
        if monitor.is_enabled():
            monitor.registry().gauge(
                "profiler_capture_seconds",
                help="duration of the last xplane capture window").set(dur)
        return self.logdir

    def __enter__(self) -> "ProfilerCapture":
        return self.start()

    def __exit__(self, *exc):
        self.stop()
        return False
