"""Declarative alert engine over the metrics substrate.

docs/OBSERVABILITY.md used to carry eight prose-only "Alert shape:"
paragraphs — rules a human had to re-derive from a dashboard.  This
module makes them machine-evaluated: an `AlertRule` states WHAT to
watch, the `AlertEngine` evaluates the rule set on cadence over a
`MetricsRegistry` snapshot (or a federated `MetricsAggregator`, which is
duck-compatible), runs each rule through a pending→firing→resolved state
machine with `for_s` hysteresis, logs every transition to the
`FlightRecorder` under the rule's own event kind, and publishes an
`alert_state{alert=,severity=}` gauge family so `/metrics` scrapes and
the `/alerts` UI route serve the same truth.

Rule kinds:

- ``threshold``   — compare an aggregated family value against a bound
                    (`checkpoint_last_age_seconds > 120`);
- ``absence``     — fire when something that was there is gone: a
                    previously-seen series vanishes, or (against an
                    aggregator) a previously-seen worker label vanishes
                    or its export goes stale past ``stale_s``;
- ``delta_rate``  — rate of increase of a counter between evaluations
                    (`serving_shed_total` climbing); an optional
                    ``unless_metric`` suppresses the breach when that
                    family ALSO increased (a `fleet_swaps_total` bump is
                    fine when `registry_published_total` moved too —
                    that is a version rollout, not a silent resize); an
                    optional ``only_if_metric`` is the mirror image —
                    the breach only counts when that family increased
                    too (a tenant being shed is STARVATION only while
                    the fleet is still doing useful work; when nothing
                    moves, the fleet is down and other rules own it);
- ``burn_rate``   — windowed average of a gauge against per-window
                    bounds, ALL windows breaching (the multi-window SLO
                    burn-rate pattern: sampled history lives in the
                    engine, no second metrics pipeline).

Evaluation is pure host math over an already-materialized snapshot —
zero device syncs, nothing at all when never called.  `evaluate(now=)`
takes an explicit clock so tests drive hysteresis deterministically.

`default_rule_pack()` ships the twelve documented shapes: checkpoint
staleness, elastic shrink, shed growth, registry fallback, watermark
lag, worker-vanished, SLO burn, swap-without-publish, radix eviction
churn, sampled-spec acceptance collapse, drift-gate stuck-paused,
tenant share starvation.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from .flightrec import GLOBAL_FLIGHT_RECORDER
from .goodput import GOODPUT_COUNTER_FAMILIES

__all__ = ["AlertRule", "AlertEngine", "default_rule_pack",
           "ALERT_STATE_GAUGE", "STATE_VALUES"]

#: the tenant-starvation co-requirement family — the fleet's "still
#: moving useful tokens" signal (the goodput ledger's serving mirror).
GOODPUT_USEFUL_FAMILY = GOODPUT_COUNTER_FAMILIES["useful"]

ALERT_STATE_GAUGE = "alert_state"

#: gauge encoding of the state machine (what `/metrics` exports).
STATE_VALUES = {"ok": 0.0, "pending": 1.0, "firing": 2.0}

_KINDS = ("threshold", "absence", "delta_rate", "burn_rate")
_OPS: Dict[str, Callable[[float, float], bool]] = {
    ">": lambda a, b: a > b, ">=": lambda a, b: a >= b,
    "<": lambda a, b: a < b, "<=": lambda a, b: a <= b,
}


@dataclass
class AlertRule:
    """One declarative rule.  `metric=None` on an ``absence`` rule means
    worker liveness (requires an aggregator source); on every other kind
    `metric` is required."""

    name: str
    kind: str
    metric: Optional[str] = None
    labels: Dict[str, str] = field(default_factory=dict)
    op: str = ">"
    value: float = 0.0
    for_s: float = 0.0
    severity: str = "ticket"
    event_kind: str = "alert"
    description: str = ""
    aggregate: str = "max"                 # max | min | sum over series
    stale_s: Optional[float] = None        # absence: export-age bound
    unless_metric: Optional[str] = None    # delta_rate suppressor
    only_if_metric: Optional[str] = None   # delta_rate co-requirement
    windows: Tuple[Tuple[float, float], ...] = ()   # burn_rate

    def __post_init__(self):
        if self.kind not in _KINDS:
            raise ValueError(f"unknown rule kind: {self.kind!r}")
        if self.op not in _OPS:
            raise ValueError(f"unknown comparison op: {self.op!r}")
        if self.kind != "absence" and not self.metric:
            raise ValueError(f"rule {self.name!r}: metric is required")
        if self.kind == "burn_rate" and not self.windows:
            raise ValueError(f"rule {self.name!r}: burn_rate needs windows")


def _series_values(snap: Dict, metric: str,
                   labels: Dict[str, str]) -> List[Tuple[Tuple, float]]:
    """Matching (label-key, value) pairs for one family; label match is
    subset (a rule with no labels matches every child).  Histograms
    contribute their cumulative count."""
    fam = snap.get(metric)
    if not fam:
        return []
    out = []
    for entry in fam.get("values", ()):
        lbl = entry.get("labels") or {}
        if any(lbl.get(k) != v for k, v in labels.items()):
            continue
        v = entry.get("value")
        if v is None:
            v = entry.get("count")
        if v is None:
            continue
        try:
            v = float(v)
        except (TypeError, ValueError):
            continue
        if v != v:                       # NaN series never breach
            continue
        out.append((tuple(sorted(lbl.items())), v))
    return out


def _aggregate(vals: Sequence[float], how: str) -> Optional[float]:
    if not vals:
        return None
    if how == "sum":
        return float(sum(vals))
    if how == "min":
        return float(min(vals))
    return float(max(vals))


class AlertEngine:
    """Evaluate a rule set over a snapshot source on demand or cadence.

    `source` is anything with `.snapshot()` (a `MetricsRegistry` or a
    `MetricsAggregator`) or a zero-arg callable returning a snapshot
    dict.  Transitions go to `recorder` (the global flight recorder by
    default); `alert_state` gauges go to `registry` (the active monitor
    registry by default, skipped when monitoring is disabled).
    """

    def __init__(self, source, rules: Sequence[AlertRule] = (), *,
                 recorder=None, registry=None):
        self._source = source
        self._rules: List[AlertRule] = []
        self._recorder = recorder if recorder is not None \
            else GLOBAL_FLIGHT_RECORDER
        self._registry = registry
        self._lock = threading.Lock()
        self._states: Dict[str, Dict] = {}
        self._prev_counters: Dict[str, Tuple[float, Dict[Tuple, float]]] = {}
        self._history: Dict[str, List[Tuple[float, float]]] = {}
        self._seen_workers: set = set()
        self._seen_series: Dict[str, set] = {}
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        for r in rules:
            self.add_rule(r)

    # -------------------------------------------------------------- rules
    def add_rule(self, rule: AlertRule) -> "AlertEngine":
        with self._lock:
            if any(r.name == rule.name for r in self._rules):
                raise ValueError(f"duplicate rule name: {rule.name!r}")
            self._rules.append(rule)
            self._states[rule.name] = {
                "name": rule.name, "kind": rule.kind, "metric": rule.metric,
                "severity": rule.severity, "event_kind": rule.event_kind,
                "description": rule.description, "for_s": rule.for_s,
                "state": "ok", "since": None, "fired_at": None,
                "resolved_at": None, "value": None, "context": {},
            }
        return self

    @property
    def rules(self) -> List[AlertRule]:
        with self._lock:
            return list(self._rules)

    # ---------------------------------------------------------- snapshot
    def _snapshot(self) -> Dict:
        src = self._source
        if callable(src) and not hasattr(src, "snapshot"):
            return src() or {}
        return src.snapshot() or {}

    # ---------------------------------------------------------- evaluate
    def evaluate(self, now: Optional[float] = None) -> List[Dict]:
        """One evaluation pass; returns the post-pass `states()` view.
        `now` is the state-machine clock (monotonic seconds by default);
        explicit values make hysteresis deterministic in tests."""
        now = time.monotonic() if now is None else float(now)
        snap = self._snapshot()
        with self._lock:
            rules = list(self._rules)
        for rule in rules:
            breach, value, ctx = self._eval_rule(rule, snap, now)
            self._transition(rule, breach, value, ctx, now)
        self._publish_gauges()
        return self.states()

    def _eval_rule(self, rule: AlertRule, snap: Dict, now: float):
        if rule.kind == "threshold":
            pairs = _series_values(snap, rule.metric, rule.labels)
            agg = _aggregate([v for _, v in pairs], rule.aggregate)
            if agg is None:
                return False, None, {}
            return _OPS[rule.op](agg, rule.value), agg, {}

        if rule.kind == "absence":
            return self._eval_absence(rule, snap, now)

        if rule.kind == "delta_rate":
            return self._eval_delta_rate(rule, snap, now)

        # burn_rate: sample the aggregated gauge into engine history,
        # breach when EVERY (window_s, bound) pair's windowed average
        # clears its bound.
        pairs = _series_values(snap, rule.metric, rule.labels)
        agg = _aggregate([v for _, v in pairs], rule.aggregate)
        hist = self._history.setdefault(rule.name, [])
        if agg is not None:
            hist.append((now, agg))
        horizon = max(w for w, _ in rule.windows)
        while hist and hist[0][0] < now - horizon:
            hist.pop(0)
        if not hist:
            return False, agg, {}
        avgs = {}
        breach = True
        for window_s, bound in rule.windows:
            sample = [v for t, v in hist if t >= now - window_s]
            if not sample:
                breach = False
                continue
            avg = sum(sample) / len(sample)
            avgs[f"avg_{int(window_s)}s"] = avg
            if not _OPS[rule.op](avg, bound):
                breach = False
        return breach, agg, avgs

    def _eval_absence(self, rule: AlertRule, snap: Dict, now: float):
        if rule.metric is None:
            # worker liveness: a previously-seen worker label gone from
            # the aggregator, or its export stale past stale_s.
            src = self._source
            if not hasattr(src, "workers"):
                return False, None, {}
            current = set(src.workers())
            self._seen_workers |= current
            missing = sorted(self._seen_workers - current)
            stale: List[str] = []
            if rule.stale_s is not None and hasattr(src, "export_ages"):
                ages = src.export_ages()
                stale = sorted(w for w, age in ages.items()
                               if age > rule.stale_s)
            gone = sorted(set(missing) | set(stale))
            ctx = {"missing": missing, "stale": stale}
            return bool(gone), float(len(gone)), ctx
        # series absence: a previously-seen label set for this family no
        # longer exported.
        pairs = _series_values(snap, rule.metric, rule.labels)
        current = {k for k, _ in pairs}
        seen = self._seen_series.setdefault(rule.name, set())
        seen |= current
        missing = seen - current
        ctx = {"missing": [dict(k) for k in sorted(missing)]}
        return bool(missing), float(len(missing)), ctx

    def _guard_increase(self, rule_key: str, metric: str, snap: Dict,
                        now: float) -> Optional[float]:
        """Total positive increase of a companion counter family since
        the previous evaluation (None on the first sighting)."""
        gpairs = dict(_series_values(snap, metric, {}))
        gprev = self._prev_counters.get(rule_key)
        self._prev_counters[rule_key] = (now, gpairs)
        if gprev is None:
            return None
        _, gold = gprev
        return sum(max(0.0, v - gold.get(k, 0.0))
                   for k, v in gpairs.items())

    def _eval_delta_rate(self, rule: AlertRule, snap: Dict, now: float):
        pairs = dict(_series_values(snap, rule.metric, rule.labels))
        prev = self._prev_counters.get(rule.name)
        self._prev_counters[rule.name] = (now, pairs)
        guard_inc = onlyif_inc = None
        if rule.unless_metric:
            guard_inc = self._guard_increase(
                rule.name + "/unless", rule.unless_metric, snap, now)
        if rule.only_if_metric:
            onlyif_inc = self._guard_increase(
                rule.name + "/only_if", rule.only_if_metric, snap, now)
        if prev is None:
            return False, None, {}
        t0, old = prev
        dt = now - t0
        if dt <= 0:
            return False, None, {}
        inc = sum(max(0.0, v - old.get(k, 0.0)) for k, v in pairs.items())
        rate = inc / dt
        ctx = {"increase": inc, "interval_s": dt}
        if rule.unless_metric:
            ctx["unless_increase"] = guard_inc or 0.0
            if guard_inc:
                return False, rate, ctx
        if rule.only_if_metric:
            ctx["only_if_increase"] = onlyif_inc or 0.0
            if not onlyif_inc:
                return False, rate, ctx
        return _OPS[rule.op](rate, rule.value), rate, ctx

    # ----------------------------------------------------- state machine
    def _transition(self, rule: AlertRule, breach: bool, value, ctx,
                    now: float):
        with self._lock:
            st = self._states[rule.name]
            prev = st["state"]
            new = prev
            if prev == "ok" and breach:
                if rule.for_s > 0:
                    new = "pending"
                    st["since"] = now
                else:
                    new = "firing"
                    st["since"] = now
                    st["fired_at"] = now
            elif prev == "pending":
                if not breach:
                    new = "ok"
                    st["since"] = None
                elif now - st["since"] >= rule.for_s:
                    new = "firing"
                    st["fired_at"] = now
            elif prev == "firing" and not breach:
                new = "ok"
                st["since"] = None
                st["resolved_at"] = now
            st["value"] = value
            st["context"] = dict(ctx)
            changed = new != prev
            if changed:
                st["state"] = new
        if changed:
            # resolved is the firing→ok edge; pending→ok is a flap that
            # never fired.
            label = "resolved" if (prev == "firing" and new == "ok") \
                else new
            try:
                self._recorder.record(
                    rule.event_kind, alert=rule.name, state=label,
                    severity=rule.severity,
                    value=value if value is not None else float("nan"))
            except Exception:
                pass

    # ----------------------------------------------------------- outputs
    def states(self) -> List[Dict]:
        """Current rule states, most urgent first (firing, pending, ok;
        pages before tickets within a band)."""
        with self._lock:
            out = [dict(s, context=dict(s["context"]))
                   for s in self._states.values()]
        rank = {"firing": 0, "pending": 1, "ok": 2}
        sev = {"page": 0, "ticket": 1, "info": 2}
        out.sort(key=lambda s: (rank.get(s["state"], 3),
                                sev.get(s["severity"], 3), s["name"]))
        return out

    def firing(self) -> List[Dict]:
        return [s for s in self.states() if s["state"] == "firing"]

    def _publish_gauges(self):
        reg = self._registry
        if reg is None:
            from deeplearning4j_tpu import monitor
            if not monitor.is_enabled():
                return
            reg = monitor.registry()
        try:
            for s in self.states():
                reg.gauge(ALERT_STATE_GAUGE, alert=s["name"],
                          severity=s["severity"]).set(
                              STATE_VALUES[s["state"]])
        except Exception:
            pass

    # ----------------------------------------------------------- cadence
    def start(self, interval_s: float = 5.0) -> "AlertEngine":
        if self._thread is None:
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._loop, args=(float(interval_s),),
                name="alert-engine", daemon=True)
            self._thread.start()
        return self

    def _loop(self, interval_s: float):
        while not self._stop.wait(interval_s):
            try:
                self.evaluate()
            except Exception:
                pass

    def stop(self):
        self._stop.set()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=5.0)

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()
        return False


# =====================================================================
# the default rule pack: the documented alert shapes, codified
# =====================================================================

def default_rule_pack(*, checkpoint_stale_s: float = 120.0,
                      elastic_min_processes: float = 1.0,
                      shed_rate_per_s: float = 1.0,
                      watermark_stale_s: float = 120.0,
                      slo_fast_burn: float = 14.0,
                      slo_fast_window_s: float = 60.0,
                      worker_stale_s: Optional[float] = None,
                      radix_evict_per_s: float = 5.0,
                      spec_accept_collapse: float = 0.05,
                      drift_paused_for_s: float = 120.0,
                      tenant_shed_rate_per_s: float = 1.0,
                      for_s: float = 5.0) -> List[AlertRule]:
    """The shipped rules, one per documented alert shape (the table in
    docs/OBSERVABILITY.md).  Rules over families a process never exports
    simply never match — one pack fits training-only, serving-only and
    federated deployments."""
    return [
        AlertRule(
            name="checkpoint-staleness", kind="threshold",
            metric="checkpoint_last_age_seconds", op=">",
            value=checkpoint_stale_s, severity="page",
            event_kind="checkpoint_stale",
            description="newest committed checkpoint older than the "
                        "configured bound — writes are stalling"),
        AlertRule(
            name="elastic-shrink", kind="threshold",
            metric="elastic_live_processes", op="<",
            value=elastic_min_processes, for_s=for_s, severity="page",
            event_kind="elastic_shrink",
            description="elastic membership below the provisioned fleet "
                        "size for longer than a relaunch should take"),
        AlertRule(
            name="shed-growth", kind="delta_rate",
            metric="serving_shed_total", op=">", value=shed_rate_per_s,
            aggregate="sum", severity="ticket", event_kind="shed_growth",
            description="SLO admission policy actively refusing work — "
                        "scale out or raise the objective"),
        AlertRule(
            name="registry-fallback", kind="delta_rate",
            metric="registry_resolve_fallback_total", op=">", value=0.0,
            aggregate="sum", severity="page",
            event_kind="registry_fallback",
            description="published zips failing checksum verification — "
                        "the fleet serves an older version than you "
                        "think"),
        AlertRule(
            name="watermark-lag", kind="threshold",
            metric="streaming_watermark_age_seconds", op=">",
            value=watermark_stale_s, severity="ticket",
            event_kind="watermark_lag",
            description="ingest watermark stalled — the producer "
                        "stopped (lag flat) or training fell behind "
                        "(lag rising)"),
        AlertRule(
            name="worker-vanished", kind="absence", metric=None,
            stale_s=worker_stale_s, severity="page",
            event_kind="worker_vanished",
            description="a previously-seen worker label left the "
                        "federated scrape — its publisher died"),
        AlertRule(
            name="slo-burn", kind="burn_rate", metric="slo_burn_rate",
            op=">", windows=((slo_fast_window_s, slo_fast_burn),),
            severity="page", event_kind="slo_burn",
            description="error budget burning faster than the fast-burn "
                        "page bound"),
        AlertRule(
            name="swap-without-publish", kind="delta_rate",
            metric="fleet_swaps_total", op=">", value=0.0,
            aggregate="sum", unless_metric="registry_published_total",
            severity="info", event_kind="swap_without_publish",
            description="fleet swapped servers with no matching publish "
                        "— the autoscaler is resizing (check "
                        "fleet_slot_count)"),
        AlertRule(
            name="radix-eviction-churn", kind="delta_rate",
            metric="serving_radix_evictions_total", op=">",
            value=radix_evict_per_s, aggregate="sum",
            severity="ticket", event_kind="radix_eviction_churn",
            description="radix prefix-cache nodes evicted faster than "
                        "they pay back — the pool is too small for the "
                        "working set and every admission re-prefills "
                        "what the last one cached"),
        AlertRule(
            name="sampled-spec-acceptance-collapse", kind="threshold",
            metric="serving_spec_accept_rate", op="<",
            value=spec_accept_collapse, aggregate="min",
            severity="ticket", event_kind="spec_acceptance_collapse",
            description="a speculative proposer's acceptance EWMA "
                        "collapsed — sampled streams are paying the "
                        "K-wide verify dispatch for ~1 token/dispatch "
                        "(check the proposer label; rejection-sampling "
                        "acceptance tracks draft/target divergence)"),
        AlertRule(
            name="drift-gate-stuck-paused", kind="threshold",
            metric="online_publish_paused", op=">=", value=1.0,
            aggregate="max", for_s=drift_paused_for_s,
            severity="ticket", event_kind="drift_gate_stuck",
            description="a DriftGate has held publishes paused past "
                        "the hysteresis window — the tenant's stream "
                        "shifted and stayed shifted, so its serving "
                        "adapter is frozen on stale data (check the "
                        "tag label for which tenant)"),
        AlertRule(
            name="tenant-share-starvation", kind="delta_rate",
            metric="fleet_tenant_shed_total", op=">",
            value=tenant_shed_rate_per_s, aggregate="sum",
            only_if_metric=GOODPUT_USEFUL_FAMILY,
            severity="ticket", event_kind="tenant_starvation",
            description="a tenant's shed rate is climbing while the "
                        "fleet is still moving useful tokens — a "
                        "fairness problem (heavy neighbor), not an "
                        "outage: check fleet_tenant_share against the "
                        "tenant's floor and the heavy tenant's "
                        "weight"),
    ]
