"""Bridges from the existing telemetry surfaces onto the registry.

- `MonitorListener` rides the `TrainingListener` bus every container
  already fans out to (`optimize/listeners.py`), turning iteration/epoch
  callbacks into registry counters/gauges/histograms. When monitoring
  is enabled the fit loops attach one automatically (see
  `monitor.extra_listeners()`), so ANY fit feeds `/metrics` without
  code changes at the call site.
- `bind_master_stats` hooks a `TrainingMasterStats` (parallel trainers'
  per-phase round timing) via its `add_listener` seam: every phase
  event lands in the registry as a labeled phase timer AND in the
  tracer as a complete-event span, so the distributed phases appear on
  the same Perfetto timeline as the single-model fit spans.
"""

from __future__ import annotations

from typing import Optional

from deeplearning4j_tpu.monitor.registry import MetricsRegistry
from deeplearning4j_tpu.monitor.tracer import Tracer

from deeplearning4j_tpu.optimize.listeners import TrainingListener


class MonitorListener(TrainingListener):
    """TrainingListener → MetricsRegistry adapter.

    Pure host-side arithmetic on values the fit loop already computed —
    no device syncs, honoring the zero-cost contract."""

    def __init__(self, registry: MetricsRegistry, model_label: str = "default"):
        self.registry = registry
        self.model_label = model_label

    def iteration_done(self, model, iteration, epoch, score, **info):
        reg = self.registry
        lbl = {"model": self.model_label}
        reg.counter("training_iterations_total",
                    help="fit iterations completed", **lbl).inc()
        batch = info.get("batch_size", 0)
        if batch:
            reg.counter("training_examples_total",
                        help="examples trained", **lbl).inc(float(batch))
        score = float(score)
        if score == score:  # skip NaN (score not read back this step)
            reg.gauge("training_score", help="last minibatch loss",
                      **lbl).set(score)
        etl_ms = info.get("etl_ms")
        if etl_ms:
            reg.histogram("training_etl_seconds",
                          help="dataset ETL time per batch",
                          **lbl).observe(float(etl_ms) / 1e3)

    def on_epoch_end(self, model, epoch):
        self.registry.counter("training_epochs_total",
                              help="fit epochs completed",
                              model=self.model_label).inc()

    def on_fit_start(self, model):
        self.registry.counter("training_fits_total",
                              help="fit() calls started",
                              model=self.model_label).inc()


def record_master_event(ev, registry: MetricsRegistry,
                        tracer: Optional[Tracer] = None,
                        t0_perf: Optional[float] = None):
    """Land one `TrainingMasterStats` phase event in the registry
    (+ tracer). `t0_perf` is the stats object's `time.perf_counter()`
    epoch: with it, spans are placed via absolute perf_counter readings
    (`complete_between`) so they align with the fit spans on the same
    tracer timeline; without it they fall back to the event's own
    relative clock."""
    phase = ev.get("phase", "unknown")
    dur_s = ev.get("duration_ms", 0.0) / 1e3
    registry.counter("parallel_phase_total",
                     help="distributed-training phase occurrences",
                     phase=phase).inc()
    registry.timer("parallel_phase_seconds",
                   help="distributed-training phase durations",
                   phase=phase).observe(dur_s)
    if tracer is not None:
        extra = {k: v for k, v in ev.items()
                 if k not in ("phase", "start_ms", "duration_ms")}
        if t0_perf is not None:
            start = t0_perf + ev.get("start_ms", 0.0) / 1e3
            tracer.complete_between(f"master/{phase}", start, start + dur_s,
                                    **extra)
        else:
            tracer.add_complete_event(
                f"master/{phase}", ev.get("start_ms", 0.0) / 1e3, dur_s,
                **extra)


def bind_master_stats(stats, registry: MetricsRegistry,
                      tracer: Optional[Tracer] = None):
    """Route every `TrainingMasterStats` phase event onto the registry
    (+ tracer). Returns `stats` for chaining."""
    t0_perf = getattr(stats, "_t0", None)

    def on_event(ev):
        record_master_event(ev, registry, tracer, t0_perf)

    stats.add_listener(on_event)
    return stats
