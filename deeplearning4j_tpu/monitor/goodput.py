"""Token-goodput ledger: classify every dispatched token-position.

The continuous-batching engine dispatches fixed-shape programs — waves
padded to pow2 widths and length buckets, decode chunks over every slot,
speculative lanes that may be rejected.  The registry's
`serving_tokens_total` counts only what was emitted; nobody could answer
"of the device token-positions we paid for, how many produced a token a
user kept?".  The `GoodputLedger` closes that gap: each dispatch site in
`serving/engine.py` classifies the token-positions of the program it
just launched into exactly one of six classes:

- ``useful``           — positions that prefilled a live prompt or
                         emitted a kept token;
- ``spec_rejected``    — valid speculative draft positions whose tokens
                         the target model rejected; also the
                         truncated-layer drafter's generation pass (its
                         real lanes are speculation overhead — they
                         never emit directly, the verify dispatch does);
- ``pad_waste``        — padding to pow2 wave widths / length buckets /
                         idle decode lanes;
- ``warmup``           — everything dispatched inside `warmup()`'s
                         compile grid (mode-routed, see below);
- ``preempt_discard``  — re-prefill of work already done once: a
                         pool-pressure preemption requeued as a
                         continuation prefills prompt+emitted again;
- ``drain``            — positions dispatched while the server drains
                         for a hot-swap: delivered, but attributed to
                         the swap window (goodput visibly dips during
                         swaps, which is the signal an operator wants).

Conservation holds *by construction*: `account()` bumps
`dispatched_total` by the same sum it distributes over the classes, so
``sum(classes) == dispatched_total`` at every instant — test-enforced
over a whole loadtest run.  All counters are host ints fed from values
the scheduler already materialized; the ledger adds ZERO device syncs
(block_until_ready-counting test, same contract as request tracing).

Modes: `set_mode("warmup")` / `set_mode("drain")` route ALL subsequent
accounting into that class while active.  Rerouting at account time (not
reclassifying later) keeps every counter monotone, so registry mirrors
never see negative deltas.

`ttft_decomposition(trace)` splits a finished request's TTFT into
queue-wait / prefill / first-emit from the host stamps `RequestTrace`
already records — no new clocks.
"""

from __future__ import annotations

from typing import Dict, Optional

__all__ = ["GOODPUT_CLASSES", "GoodputLedger", "ttft_decomposition",
           "GOODPUT_COUNTER_FAMILIES", "GOODPUT_FRACTION_GAUGE"]

GOODPUT_CLASSES = ("useful", "spec_rejected", "pad_waste", "warmup",
                   "preempt_discard", "drain")

#: registry family names the serving mirror publishes (one counter per
#: class plus the rolling fraction gauge) — single source of truth for
#: server.py, the loadtest ledger and the verify smoke.
GOODPUT_COUNTER_FAMILIES = {
    c: f"serving_tokens_{c}_total" for c in GOODPUT_CLASSES
}
GOODPUT_FRACTION_GAUGE = "serving_goodput_fraction"


class GoodputLedger:
    """Host-side token-position accounting for one engine.

    Thread-safety: all mutation happens on the scheduler thread (the
    same thread that runs every dispatch), reads from other threads see
    at worst a value one dispatch old — same contract as the engine's
    other host counters.
    """

    __slots__ = ("dispatched_total", "classes", "_mode")

    def __init__(self):
        self.dispatched_total = 0
        self.classes: Dict[str, int] = {c: 0 for c in GOODPUT_CLASSES}
        self._mode: Optional[str] = None

    # ------------------------------------------------------------- mode
    def set_mode(self, mode: Optional[str]):
        """Route ALL subsequent accounting into `mode` ("warmup" /
        "drain"), or back to per-class accounting (None)."""
        if mode is not None and mode not in ("warmup", "drain"):
            raise ValueError(f"unknown ledger mode: {mode!r}")
        self._mode = mode

    @property
    def mode(self) -> Optional[str]:
        return self._mode

    # ------------------------------------------------------- accounting
    def account(self, *, useful: int = 0, spec_rejected: int = 0,
                pad_waste: int = 0, preempt_discard: int = 0):
        """Classify one dispatch's token-positions.  The sum of the
        keyword arguments IS the dispatch total — there is no separate
        total to drift from, so conservation cannot break."""
        total = useful + spec_rejected + pad_waste + preempt_discard
        if total <= 0:
            return
        if min(useful, spec_rejected, pad_waste, preempt_discard) < 0:
            raise ValueError("goodput classes must be non-negative")
        if self._mode is not None:
            self.classes[self._mode] += total
        else:
            self.classes["useful"] += useful
            self.classes["spec_rejected"] += spec_rejected
            self.classes["pad_waste"] += pad_waste
            self.classes["preempt_discard"] += preempt_discard
        self.dispatched_total += total

    # ------------------------------------------------------------ reads
    def goodput_fraction(self) -> float:
        """useful / dispatched — 0.0 before any dispatch (an honest
        zero, never a flattering 1.0)."""
        if self.dispatched_total <= 0:
            return 0.0
        return self.classes["useful"] / self.dispatched_total

    def conserved(self) -> bool:
        return sum(self.classes.values()) == self.dispatched_total

    def snapshot(self) -> Dict:
        out = dict(self.classes)
        out["dispatched_total"] = self.dispatched_total
        out["goodput_fraction"] = self.goodput_fraction()
        return out


# =====================================================================
# TTFT decomposition from RequestTrace host stamps
# =====================================================================

def ttft_decomposition(trace) -> Optional[Dict[str, float]]:
    """Split a finished request's time-to-first-token into
    queue-wait / prefill / first-emit.

    Accepts a `RequestTrace` or its `to_dict()` form.  All inputs are
    stamps the scheduler already recorded: the "queued" phase (submit →
    admission wave), the "prefill" phase (the admission dispatch) and
    the `ttft_s` annotation `_finish` writes.  ``first_emit`` is the
    residual — prefill completion to the consumer seeing the token
    (queue handoff + stream wakeup) — clamped at zero.  Returns None
    when the trace never reached prefill (shed before admission).
    """
    if hasattr(trace, "to_dict"):
        phases = trace.phases
        meta = trace.meta
    else:
        phases = trace.get("phases") or []
        meta = trace.get("meta") or {}
    spans = {}
    for p in phases:
        name = p["name"]
        if name in ("queued", "prefill") and name not in spans:
            spans[name] = max(0.0, float(p["t1"]) - float(p["t0"]))
    if "prefill" not in spans:
        return None
    queue_wait = spans.get("queued", 0.0)
    prefill = spans["prefill"]
    ttft = meta.get("ttft_s")
    if ttft is None:
        ttft = queue_wait + prefill
    ttft = float(ttft)
    first_emit = max(0.0, ttft - queue_wait - prefill)
    return {"queue_wait_s": queue_wait, "prefill_s": prefill,
            "first_emit_s": first_emit, "ttft_s": ttft}
