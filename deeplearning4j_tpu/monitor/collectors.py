"""JAX runtime collectors feeding the metrics registry.

Three windows into the runtime the host-side spans can't see:

- `JitCompileCollector` — compile-cache tracking via `jax.monitoring`
  duration events (`/jax/core/compile/*`): compile count + cumulative
  compile seconds, so bench/dashboards can split warmup (trace +
  lowering + XLA compile) from steady-state device time.
- `DeviceMemoryCollector` — per-device HBM gauges from
  `device.memory_stats()` where the backend provides it (TPU/GPU; CPU
  returns None and the collector reports itself unavailable).
- transfer counters — host→device placements recorded by the trainers'
  placement helpers (`parallel/placement.gput`) when monitoring is on.

None of these insert device syncs: compile events are host callbacks,
`memory_stats()` reads allocator bookkeeping, and transfer counters
count the placements the program was doing anyway — the "zero extra
syncs when disabled" contract (see parallel/stats.py) extends to
"zero extra syncs when ENABLED" for every collector here.
"""

from __future__ import annotations

from typing import Dict, Optional

from deeplearning4j_tpu.monitor.registry import MetricsRegistry


class JitCompileCollector:
    """Counts jit compiles and accumulates compile seconds by stage.

    Registers a `jax.monitoring` duration listener; jax's listener list
    is append-only (`clear_event_listeners` wipes everyone), so
    `uninstall()` just deactivates the callback.
    """

    _PREFIX = "/jax/core/compile/"
    # the event that fires once per actual XLA compilation
    _BACKEND_EVENT = "/jax/core/compile/backend_compile_duration"

    def __init__(self, registry: MetricsRegistry):
        self.registry = registry
        self._active = False
        self._registered = False

    def install(self) -> "JitCompileCollector":
        self._active = True
        if not self._registered:
            import jax.monitoring
            jax.monitoring.register_event_duration_secs_listener(self._on_event)
            self._registered = True
        return self

    def uninstall(self):
        self._active = False

    def _on_event(self, event: str, duration_secs: float, **kwargs):
        if not self._active or not event.startswith(self._PREFIX):
            return
        stage = event[len(self._PREFIX):]
        self.registry.counter(
            "jax_compile_seconds_total",
            help="cumulative jit compile time by stage",
            stage=stage).inc(duration_secs)
        if event == self._BACKEND_EVENT:
            self.registry.counter(
                "jax_compiles_total",
                help="number of XLA backend compilations").inc()

    # convenience readers (bench warmup/steady-state split)
    def compile_count(self) -> float:
        return self.registry.counter("jax_compiles_total").value

    def compile_seconds(self) -> float:
        total = 0.0
        fam = self.registry._families.get("jax_compile_seconds_total")
        if fam is not None:
            total = sum(c.value for c in fam.children.values())
        return total


class DeviceMemoryCollector:
    """Device memory gauges from `device.memory_stats()`.

    `collect()` refreshes the gauges; call it wherever a fresh reading
    matters (epoch end, /metrics scrape). Backends without allocator
    stats (XLA:CPU) make this a no-op with `available == False`."""

    _KEYS = ("bytes_in_use", "peak_bytes_in_use", "bytes_limit",
             "largest_alloc_size")

    def __init__(self, registry: MetricsRegistry):
        self.registry = registry
        self.available: Optional[bool] = None

    def collect(self) -> bool:
        import jax
        seen = False
        for d in jax.local_devices():
            try:
                stats = d.memory_stats()
            except Exception:  # noqa: BLE001 — backend-dependent API
                stats = None
            if not stats:
                continue
            seen = True
            for key in self._KEYS:
                if key in stats:
                    self.registry.gauge(
                        "jax_device_memory_bytes",
                        help="device allocator stats",
                        device=str(d.id), kind=key).set(float(stats[key]))
        self.available = seen
        return seen


def record_transfer(registry: MetricsRegistry, nbytes: int, direction: str = "h2d"):
    """One host↔device placement: bump count + byte counters."""
    registry.counter("jax_transfers_total",
                     help="array placements host<->device",
                     direction=direction).inc()
    registry.counter("jax_transfer_bytes_total",
                     help="bytes moved host<->device",
                     direction=direction).inc(float(max(0, nbytes)))
