"""Control-plane flight recorder: a bounded durable ring of the events
that explain an incident after the fact.

The metrics registry answers "what is the p99 now"; the flight recorder
answers "what did the control plane DO in the last ten minutes" —
publishes, swaps, drains, autoscale decisions, drift-gate trips, elastic
reconfigurations, watchdog halts, shed bursts. This is the postmortem
half of the fleet-health machinery TPU fleets lean on (arXiv:2606.15870):
when a swap strands requests or an autoscaler flaps, the first question
is the ordered event log, not a gauge.

Design points:

- **Always on.** Control-plane events are rare (Hz, not kHz) and tiny,
  so recording does not route through `monitor.is_enabled()` — a crash
  in a run that never enabled metrics still leaves a usable ring.
- **Bounded.** A deque ring (default 4096) caps memory; `dropped`
  counts evictions so a dump is honest about missing history.
- **Durable (optional).** `path=` appends every event as one JSONL line
  at record time — the ring survives the process only if asked to.
- **Dump on error.** `dump(path)` writes the current ring; callers hang
  it off their exception paths.

Pure stdlib, thread-safe, no JAX imports.
"""

from __future__ import annotations

import json
import threading
import time
from collections import deque
from typing import Dict, List, Optional


class FlightRecorder:
    def __init__(self, capacity: int = 4096, path: Optional[str] = None):
        self._lock = threading.Lock()
        self._ring: deque = deque(maxlen=int(capacity))
        self._path = path
        self._seq = 0
        #: events evicted from the ring (still in the durable log, if any)
        self.dropped = 0

    # ---------------------------------------------------------- recording
    def record(self, kind: str, **fields) -> Dict:
        """Append one event. `kind` is the event type (e.g. "swap",
        "drift_trip"); fields are JSON-friendly details."""
        ev = {"ts": time.time(), "kind": str(kind), **fields}
        line = None
        with self._lock:
            self._seq += 1
            ev["seq"] = self._seq
            if len(self._ring) == self._ring.maxlen:
                self.dropped += 1
            self._ring.append(ev)
            if self._path is not None:
                line = json.dumps(ev, default=str)
        if line is not None:
            try:
                with open(self._path, "a") as f:
                    f.write(line + "\n")
            except OSError:
                pass  # the recorder must never take down the control plane
        return ev

    def attach_file(self, path: Optional[str]):
        """Point (or un-point) the durable JSONL sink."""
        with self._lock:
            self._path = path

    # ------------------------------------------------------------ queries
    def events(self, kind: Optional[str] = None,
               last: Optional[int] = None) -> List[Dict]:
        with self._lock:
            evs = list(self._ring)
        if kind is not None:
            evs = [e for e in evs if e["kind"] == kind]
        if last is not None:
            evs = evs[-int(last):]
        return evs

    def __len__(self) -> int:
        with self._lock:
            return len(self._ring)

    # ------------------------------------------------------------- export
    def dump(self, path: Optional[str] = None) -> str:
        """Serialize the ring; write JSONL to `path` if given, return the
        text either way. Called from error paths, so it never raises on
        I/O failure."""
        evs = self.events()
        text = "\n".join(json.dumps(e, default=str) for e in evs)
        if text:
            text += "\n"
        if path is not None:
            try:
                with open(path, "w") as f:
                    f.write(text)
            except OSError:
                pass
        return text

    def clear(self):
        with self._lock:
            self._ring.clear()
            self.dropped = 0


#: process-global recorder — control-plane call sites record here
GLOBAL_FLIGHT_RECORDER = FlightRecorder()


def flight_recorder() -> FlightRecorder:
    return GLOBAL_FLIGHT_RECORDER
