"""Unified telemetry core: metrics registry + span tracer + collectors.

One substrate answering "where did the step time go" across host, XLA
compile, and device (the per-phase timeline + counters discipline of
TensorFlow's runtime instrumentation, arXiv:1605.08695; the fleet
efficiency/resilience tracking the TPU survey arXiv:2606.15870 leans
on) — replacing the scattered clocks in `optimize/listeners.py`,
`ui/stats.py` and `parallel/stats.py` with one registry + one tracer
and three sinks:

- Prometheus text exposition at the UIServer's `/metrics` route,
- Chrome trace-event JSON (`export_chrome_trace`) for Perfetto,
- JSONL event logs (`Tracer.export_jsonl`, `MetricsRegistry.dump_jsonl`).

Usage::

    from deeplearning4j_tpu import monitor
    monitor.enable()                 # global registry + tracer live
    net.fit(x, y, epochs=2)          # spans + counters flow automatically
    monitor.tracer().export_chrome_trace("fit.trace.json")
    print(monitor.registry().exposition())

Overhead contract: with monitoring DISABLED (the default) the fit loops
pay one attribute check per iteration and insert **zero** additional
`block_until_ready` device syncs; enabling the registry/tracer adds
host-side float math only. The only opt-in syncs in the framework
remain `PerformanceListener(sync=True)` and `TrainingMasterStats`
phase timing — exactly as `parallel/stats.py` documents.
"""

from __future__ import annotations

import threading
from typing import List, Optional

from deeplearning4j_tpu.monitor.registry import (
    GLOBAL_REGISTRY,
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    Timer,
)
from deeplearning4j_tpu.monitor.tracer import (
    GLOBAL_TRACER,
    NOOP_SPAN,
    Span,
    Tracer,
)
from deeplearning4j_tpu.monitor.collectors import (
    DeviceMemoryCollector,
    JitCompileCollector,
    record_transfer as _record_transfer_impl,
)
from deeplearning4j_tpu.monitor.listener import MonitorListener, bind_master_stats
from deeplearning4j_tpu.monitor import diagnostics
from deeplearning4j_tpu.monitor.diagnostics import (
    Diagnostics,
    DiagnosticsConfig,
    NonFiniteGradientsError,
    resolve_diagnostics,
)
from deeplearning4j_tpu.monitor import xprof
from deeplearning4j_tpu.monitor.xprof import (
    ProfilerCapture,
    publish_cost_report,
    roofline,
)
from deeplearning4j_tpu.monitor import reqtrace
from deeplearning4j_tpu.monitor.reqtrace import (
    RequestTrace,
    clear_exemplar_sink,
    mint_trace_id,
    set_exemplar_sink,
)
from deeplearning4j_tpu.monitor import federate
from deeplearning4j_tpu.monitor.federate import (
    FederationCollector,
    FederationPublisher,
    MetricsAggregator,
    export_snapshot,
)
from deeplearning4j_tpu.monitor import slo
from deeplearning4j_tpu.monitor.slo import SLOObjective, SLOTracker
from deeplearning4j_tpu.monitor import flightrec
from deeplearning4j_tpu.monitor.flightrec import (
    GLOBAL_FLIGHT_RECORDER,
    FlightRecorder,
    flight_recorder,
)
from deeplearning4j_tpu.monitor import goodput
from deeplearning4j_tpu.monitor.goodput import (
    GOODPUT_CLASSES,
    GoodputLedger,
    ttft_decomposition,
)
from deeplearning4j_tpu.monitor import alerts
from deeplearning4j_tpu.monitor.alerts import (
    AlertEngine,
    AlertRule,
    default_rule_pack,
)

__all__ = [
    "MetricsRegistry", "Counter", "Gauge", "Histogram", "Timer",
    "Tracer", "Span", "MonitorListener",
    "JitCompileCollector", "DeviceMemoryCollector",
    "enable", "disable", "is_enabled", "enabled", "registry", "tracer",
    "span", "record_transfer", "bind_master_stats", "attach_master_stats",
    "extra_listeners", "compile_collector", "memory_collector",
    "xprof", "ProfilerCapture", "roofline", "publish_cost_report",
    "diagnostics", "Diagnostics", "DiagnosticsConfig",
    "NonFiniteGradientsError", "resolve_diagnostics",
    "reqtrace", "RequestTrace", "mint_trace_id",
    "set_exemplar_sink", "clear_exemplar_sink",
    "federate", "MetricsAggregator", "FederationPublisher",
    "FederationCollector", "export_snapshot",
    "slo", "SLOObjective", "SLOTracker",
    "flightrec", "FlightRecorder", "flight_recorder",
    "GLOBAL_FLIGHT_RECORDER",
    "goodput", "GoodputLedger", "GOODPUT_CLASSES", "ttft_decomposition",
    "alerts", "AlertEngine", "AlertRule", "default_rule_pack",
]


class _MonitorState:
    def __init__(self):
        self.lock = threading.Lock()
        self.enabled = False
        self.registry: MetricsRegistry = GLOBAL_REGISTRY
        self.tracer: Tracer = GLOBAL_TRACER
        self.listener: Optional[MonitorListener] = None
        self.compile_collector: Optional[JitCompileCollector] = None
        self.memory_collector: Optional[DeviceMemoryCollector] = None


_STATE = _MonitorState()


def enable(registry: Optional[MetricsRegistry] = None,
           tracer: Optional[Tracer] = None, *,
           jit_compile: bool = True,
           device_memory: bool = True) -> MetricsRegistry:
    """Turn the telemetry substrate on (idempotent). Returns the active
    registry. `jit_compile` installs the compile-event collector;
    `device_memory` creates the HBM gauge collector (a no-op on
    backends without `memory_stats()`). Neither inserts device syncs."""
    with _STATE.lock:
        if registry is not None:
            _STATE.registry = registry
        if tracer is not None:
            _STATE.tracer = tracer
        _STATE.tracer.enabled = True
        # surface ring-buffer overflow: the tracer drops its OLDEST
        # event silently, so the loss count must be a visible metric
        _STATE.tracer._drop_counter = _STATE.registry.counter(
            "tracer_events_dropped_total",
            help="trace events evicted by the tracer ring buffer")
        _STATE.listener = MonitorListener(_STATE.registry)
        # a collector pointed at a superseded registry must be torn down
        # (jax's listener list is append-only: an orphaned active
        # collector would keep feeding — and pinning — the old registry)
        if (_STATE.compile_collector is not None
                and _STATE.compile_collector.registry is not _STATE.registry):
            _STATE.compile_collector.uninstall()
            _STATE.compile_collector = None
        if jit_compile:
            if _STATE.compile_collector is None:
                _STATE.compile_collector = JitCompileCollector(_STATE.registry)
            _STATE.compile_collector.install()
        elif _STATE.compile_collector is not None:
            _STATE.compile_collector.uninstall()
        if device_memory:
            _STATE.memory_collector = DeviceMemoryCollector(_STATE.registry)
        else:
            _STATE.memory_collector = None
        _STATE.enabled = True
        return _STATE.registry


def disable():
    """Back to zero-cost: fit loops skip spans/counters entirely."""
    with _STATE.lock:
        _STATE.enabled = False
        _STATE.tracer.enabled = False
        if _STATE.compile_collector is not None:
            _STATE.compile_collector.uninstall()
        _STATE.listener = None


def is_enabled() -> bool:
    return _STATE.enabled


enabled = is_enabled  # alias


def registry() -> MetricsRegistry:
    return _STATE.registry


def resolve_cached_metrics(obj, cache_attr: str, build):
    """Shared resolve-and-cache for hot-loop metric families (the
    serving scheduler, fleet publisher, router, registry and the
    ParallelInference collector all use this): None when monitoring is
    off; otherwise whatever `build(registry)` returns, resolved ONCE
    per active registry — child lookups hit the registry lock, and an
    `enable(registry=)` swap invalidates the cache by identity. The
    cache lives on `obj.<cache_attr>` as an `(registry, families)`
    pair."""
    if not is_enabled():
        return None
    reg = _STATE.registry
    cache = getattr(obj, cache_attr, None)
    if cache is not None and cache[0] is reg:
        return cache[1]
    m = build(reg)
    setattr(obj, cache_attr, (reg, m))
    return m


def tracer() -> Tracer:
    return _STATE.tracer


def compile_collector() -> Optional[JitCompileCollector]:
    return _STATE.compile_collector


def memory_collector() -> Optional[DeviceMemoryCollector]:
    return _STATE.memory_collector


def span(name: str, **args):
    """`with monitor.span("fit/forward_backward"): ...` — NOOP_SPAN when
    disabled (no allocation, no clock read)."""
    if not _STATE.enabled:
        return NOOP_SPAN
    return _STATE.tracer.span(name, **args)


def record_transfer(nbytes: int, direction: str = "h2d"):
    """Host↔device placement counter hook (called by
    `parallel/placement.gput`); no-op when disabled."""
    if _STATE.enabled:
        _record_transfer_impl(_STATE.registry, nbytes, direction)


def extra_listeners() -> List:
    """The auto-attached listener set for fit loops: `[MonitorListener]`
    when enabled, `[]` when not. Containers call this when composing
    their listener bus so every fit feeds the registry."""
    l = _STATE.listener
    return [l] if (_STATE.enabled and l is not None) else []


def attach_master_stats(stats):
    """Route a TrainingMasterStats' phase events onto the active
    registry/tracer (no-op when disabled; idempotent per stats object —
    the trainers call this at every fit()). The binding resolves the
    registry/tracer at EVENT time, so a later `enable(registry=...)`
    swap redirects an already-bound stats object to the new sinks (and
    `disable()` mutes it). Returns `stats`."""
    if (_STATE.enabled and stats is not None
            and not getattr(stats, "_monitor_bound", False)):
        from deeplearning4j_tpu.monitor.listener import record_master_event
        t0_perf = getattr(stats, "_t0", None)

        def on_event(ev):
            if _STATE.enabled:
                record_master_event(ev, _STATE.registry, _STATE.tracer,
                                    t0_perf)

        stats.add_listener(on_event)
        stats._monitor_bound = True
    return stats
