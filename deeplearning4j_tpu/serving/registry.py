"""ModelRegistry — the fleet tier's versioned model store.

The deployment plane the TensorFlow system paper (arXiv:1605.08695)
frames as where a runtime earns its keep: serving is not one model but
a *lifecycle* of named, versioned models being published, resolved and
retired while traffic flows. The store is a directory tree of the
existing atomic+checksummed ModelSerializer zips:

    <root>/<name>/v<version>.zip

Contracts:

- **Publish is rename-cheap and one-winner.** The zip is assembled at
  a hidden tmp path (ModelSerializer's own tmp+fsync+os.replace makes
  that write atomic), then *claimed* via `os.link(tmp, final)` — link
  fails with EEXIST when the version is already taken, so a concurrent
  publish of the same `(name, version)` resolves to EXACTLY one winner
  (the loser raises `VersionConflictError`; auto-versioned publishes
  retry at the next free number instead). A crash mid-publish leaves a
  complete zip or an ignored tmp orphan, never a torn version.
- **Resolve verifies before it trusts.** `resolve(name, "latest")`
  walks versions newest-first; every zip's per-array crc32 set is
  verified by `ModelSerializer.restore_model`, and a corrupt newer
  version falls back to the previous one with a logged warning (the
  `fault/resume.py` semantics — `registry_resolve_fallback_total`
  counts the degradations). Only when EVERY version fails does
  `CheckpointCorruptError` propagate, naming each candidate tried. An
  EXPLICIT version pin fails hard on corruption — a caller who asked
  for v7 must not silently get v6.
- **Retention mirrors the AsyncCheckpointer policy.** Keep the newest
  `keep_last` versions plus every `keep_every`-th, GC the rest — but
  NEVER a pinned version (`pin()`/`unpin()`; the FleetServer pins what
  it serves, so retention can never delete the weights a live engine
  is decoding from).
- **Checkpoint-as-publish is a one-liner.**
  `registry.publish_listener(name, frequency=N)` returns a
  CheckpointListener-compatible TrainingListener (same `step_boundary`
  discipline, so fused multi-step programs never publish a mid-group
  params/iteration mismatch) — attach it to any fit loop and every N
  steps becomes a served release (the ROADMAP's streaming-training
  loop publishes into exactly this seam).
"""

from __future__ import annotations

import logging
import os
import threading
import uuid
from pathlib import Path
from typing import Dict, List, Optional, Set, Tuple, Union

from deeplearning4j_tpu.fault.errors import CheckpointCorruptError
from deeplearning4j_tpu.monitor.flightrec import GLOBAL_FLIGHT_RECORDER
from deeplearning4j_tpu.optimize.listeners import TrainingListener
from deeplearning4j_tpu.util.serializer import ModelSerializer

log = logging.getLogger("deeplearning4j_tpu.serving.registry")


class VersionConflictError(RuntimeError):
    """An explicit `(name, version)` publish lost the one-winner race:
    that version already exists (another publisher claimed it first).
    Re-publish without `version=` to take the next free number."""


def _version_of(p: Path) -> Optional[int]:
    n = p.name
    if not (n.startswith("v") and n.endswith(".zip")):
        return None
    try:
        return int(n[1:-4])
    except ValueError:
        return None


class ModelRegistry:
    """Named+versioned model store over ModelSerializer zips.

    Thread-safe for concurrent publish/resolve from one process;
    cross-process safety comes from the filesystem claim protocol
    itself (link-based one-winner publish, atomic zip commits)."""

    def __init__(self, root: Union[str, Path], *, keep_last: int = 3,
                 keep_every: Optional[int] = None):
        if keep_last < 1:
            raise ValueError(f"keep_last must be >= 1, got {keep_last}")
        if keep_every is not None and keep_every < 1:
            raise ValueError(f"keep_every must be >= 1, got {keep_every}")
        self.root = Path(root)
        self.root.mkdir(parents=True, exist_ok=True)
        self.keep_last = keep_last
        self.keep_every = keep_every
        self._lock = threading.Lock()
        self._pinned: Set[Tuple[str, int]] = set()
        self._adapter_pinned: Set[Tuple[str, str, int]] = set()
        self._metrics_cache = None

    # ------------------------------------------------------------- layout
    def model_dir(self, name: str) -> Path:
        if not name or "/" in name or name.startswith("."):
            raise ValueError(f"invalid model name {name!r}")
        return self.root / name

    def path(self, name: str, version: int) -> Path:
        return self.model_dir(name) / f"v{int(version)}.zip"

    def models(self) -> List[str]:
        if not self.root.exists():
            return []
        return sorted(d.name for d in self.root.iterdir()
                      if d.is_dir() and not d.name.startswith(".")
                      and self.versions(d.name))

    def versions(self, name: str) -> List[int]:
        """Committed versions of `name`, ascending (tmp orphans and
        foreign files are ignored)."""
        d = self.model_dir(name)
        if not d.exists():
            return []
        out = [v for v in (_version_of(p) for p in d.iterdir())
               if v is not None]
        return sorted(out)

    def latest(self, name: str) -> Optional[int]:
        vs = self.versions(name)
        return vs[-1] if vs else None

    # ------------------------------------------------------------ metrics
    def _metrics(self):
        from deeplearning4j_tpu import monitor
        return monitor.resolve_cached_metrics(
            self, "_metrics_cache", lambda reg: {
                "published": lambda name: reg.counter(
                    "registry_published_total",
                    "model versions published", model=name),
                "models": reg.gauge(
                    "registry_models",
                    "distinct model names in the registry"),
                "versions": lambda name: reg.gauge(
                    "registry_versions",
                    "committed versions currently retained",
                    model=name),
                "gc": reg.counter("registry_gc_total",
                                  "versions deleted by retention GC"),
                "fallback": reg.counter(
                    "registry_resolve_fallback_total",
                    "corrupt-version fallbacks during resolve"),
            })

    def _publish_gauges(self, name: str, m):
        if m is None:
            return
        m["models"].set(len(self.models()))
        m["versions"](name).set(len(self.versions(name)))

    # ------------------------------------------------------------ publish
    def publish(self, name: str, net, *, version: Optional[int] = None,
                save_updater: bool = False, normalizer=None) -> int:
        """Publish `net` as a new version of `name`; returns the version
        committed. `version=None` takes the next free number (retrying
        past concurrent publishers); an explicit `version` that already
        exists raises `VersionConflictError` — exactly one of any set
        of concurrent same-version publishers wins.

        `save_updater=False` by default: a served release needs weights
        and normalizer state, not optimizer slots (pass True to keep
        the zip resumable as a training checkpoint too).

        `normalizer`: a fitted DataNormalization (e.g. a
        `WindowedStandardize.snapshot()`) baked INTO the zip before the
        claim — the release carries the input statistics it trained
        under (`ModelSerializer.restore_normalizer_from_file` reads it
        back)."""
        d = self.model_dir(name)
        d.mkdir(parents=True, exist_ok=True)
        tmp = d / f".publish-{os.getpid()}-{uuid.uuid4().hex[:8]}.tmp.zip"
        try:
            ModelSerializer.write_model(net, tmp, save_updater=save_updater)
            if normalizer is not None:
                ModelSerializer.add_normalizer_to_model(tmp, normalizer)
            if version is not None:
                committed = self._claim(tmp, name, int(version))
                if committed is None:
                    raise VersionConflictError(
                        f"{name} v{version} already exists — a concurrent "
                        f"publish won the claim; publish without version= "
                        f"to take the next free number")
            else:
                while True:
                    nxt = (self.latest(name) or 0) + 1
                    committed = self._claim(tmp, name, nxt)
                    if committed is not None:
                        break
        finally:
            if tmp.exists():
                tmp.unlink()
        self._fsync_dir(d)
        m = self._metrics()
        if m is not None:
            m["published"](name).inc()
        self._gc(name, m)
        self._publish_gauges(name, m)
        GLOBAL_FLIGHT_RECORDER.record("publish", model=name,
                                      version=committed)
        log.info("published %s v%d -> %s", name, committed,
                 self.path(name, committed))
        return committed

    def _claim(self, tmp: Path, name: str, version: int) -> Optional[int]:
        """Claim `version` by hard-linking the finished tmp zip to the
        final path: `os.link` is atomic and fails with EEXIST when the
        version is already taken — the one-winner primitive."""
        final = self.path(name, version)
        try:
            os.link(tmp, final)
            return version
        except FileExistsError:
            return None

    @staticmethod
    def _fsync_dir(d: Path):
        try:
            fd = os.open(d, os.O_RDONLY)
            try:
                os.fsync(fd)
            finally:
                os.close(fd)
        except OSError:  # platform without directory fsync
            pass

    # ------------------------------------------------------------ resolve
    def resolve(self, name: str, version: Union[int, str] = "latest", *,
                load_updater: bool = False):
        """Load a model from the registry; returns ``(net, version)``.

        `version="latest"` walks newest-first with corrupt-zip fallback
        (each failure logged + counted); an explicit integer version
        verifies that exact zip and raises `CheckpointCorruptError` on
        damage — no silent substitution under a pin."""
        vs = self.versions(name)
        if not vs:
            raise FileNotFoundError(
                f"no published versions of {name!r} under {self.root} "
                f"(known models: {self.models()})")
        if version != "latest":
            v = int(version)
            if v not in vs:
                raise FileNotFoundError(
                    f"{name} v{v} is not in the registry "
                    f"(have {vs})")
            net = ModelSerializer.restore_model(
                self.path(name, v), load_updater=load_updater)
            return net, v
        m = self._metrics()
        tried = []
        for v in reversed(vs):
            try:
                net = ModelSerializer.restore_model(
                    self.path(name, v), load_updater=load_updater)
                return net, v
            except CheckpointCorruptError as e:
                log.warning(
                    "%s v%d is corrupt (%s); falling back to the "
                    "previous version", name, v, e)
                if m is not None:
                    m["fallback"].inc()
                tried.append((v, e))
        detail = "; ".join(f"v{v}: {e}" for v, e in tried)
        raise CheckpointCorruptError(
            f"every published version of {name!r} failed verification "
            f"({len(tried)} candidates tried) — {detail}")

    # ---------------------------------------------------------- retention
    def _pin_marker(self, name: str, version: int) -> Path:
        return self.model_dir(name) / f".pin-v{int(version)}.{os.getpid()}"

    def pin(self, name: str, version: int):
        """Protect `(name, version)` from retention GC — the
        currently-served contract: a FleetServer pins every version an
        engine is decoding from, so GC can never delete live weights.

        Pins are ALSO recorded as on-disk markers
        (`.pin-v<version>.<pid>`): retention runs in whichever process
        publishes (e.g. a trainer with a publish listener over the
        same root a separate serving process reads), and an in-memory
        set would be invisible to it. GC honors markers whose pid is
        still alive and sweeps stale ones from dead processes."""
        with self._lock:
            self._pinned.add((name, int(version)))
        d = self.model_dir(name)
        d.mkdir(parents=True, exist_ok=True)
        try:
            self._pin_marker(name, version).touch()
        except OSError:
            pass

    def unpin(self, name: str, version: int):
        with self._lock:
            self._pinned.discard((name, int(version)))
        try:
            self._pin_marker(name, version).unlink()
        except OSError:
            pass   # marker already gone (or never written)
        # a version that outlived its pin only because it was pinned
        # gets collected at the next publish; sweep now so undeploys
        # don't leave strays until then
        self._gc(name, self._metrics())

    def pinned(self) -> Set[Tuple[str, int]]:
        """THIS process's pins (the serving process's own view);
        cross-process protection rides the on-disk markers."""
        with self._lock:
            return set(self._pinned)

    @staticmethod
    def _pid_alive(pid: int) -> bool:
        try:
            os.kill(pid, 0)
            return True
        except ProcessLookupError:
            return False
        except OSError:   # e.g. EPERM: alive under another uid
            return True

    def _marker_pins(self, name: str) -> Set[int]:
        """Versions pinned by ANY live process (marker files); stale
        markers from dead pids are swept here."""
        import re
        keep: Set[int] = set()
        d = self.model_dir(name)
        if not d.exists():
            return keep
        for p in d.glob(".pin-v*.*"):
            m = re.fullmatch(r"\.pin-v(\d+)\.(\d+)", p.name)
            if not m:
                continue
            v, pid = int(m.group(1)), int(m.group(2))
            if pid == os.getpid() or self._pid_alive(pid):
                keep.add(v)
            else:
                try:
                    p.unlink()
                except OSError:
                    pass
        return keep

    def _retained(self, name: str, vs: List[int]) -> Set[int]:
        keep = set(vs[-self.keep_last:])
        if self.keep_every:
            keep.update(v for v in vs if v % self.keep_every == 0)
        with self._lock:
            keep.update(v for n, v in self._pinned if n == name)
        keep.update(self._marker_pins(name))
        return keep

    def _gc(self, name: str, m=None):
        vs = self.versions(name)
        keep = self._retained(name, vs)
        dropped = 0
        for v in vs:
            if v in keep:
                continue
            try:
                self.path(name, v).unlink()
                dropped += 1
                log.info("retention GC dropped %s v%d", name, v)
            except OSError:
                pass
        # stale publish tmp orphans (a killed publisher's leftovers).
        # AGE-GATED: a fresh tmp is very likely a CONCURRENT publisher
        # mid-write — unlinking it between its write_model and its
        # link-claim would turn the loser's VersionConflictError into
        # a FileNotFoundError and break the one-winner contract
        import time as _time
        d = self.model_dir(name)
        cutoff = _time.time() - 3600.0
        for p in d.glob(".publish-*.tmp.zip"):
            try:
                if p.stat().st_mtime < cutoff:
                    p.unlink()
            except OSError:
                pass
        if dropped and m is not None:
            m["gc"].inc(dropped)

    # ----------------------------------------------------- adapter store
    # Per-tenant LoRA adapter deltas (tenancy/lora.py): the publish
    # unit of the multi-tenant fleet. Layout mirrors the model store
    # one level down, with its own version sequence per tenant:
    #
    #     <root>/<name>/adapters/<tenant>/v<version>.zip
    #
    # Same contracts: one-winner link claim, newest-first resolve with
    # corrupt-artifact fallback, retention that never collects a
    # pinned (= served) adapter — pins ride `.pin-v<v>.<pid>` markers
    # in the tenant directory so a separate serving process is visible
    # to the publisher's GC.

    def adapter_dir(self, name: str, tenant: str) -> Path:
        if not tenant or "/" in tenant or tenant.startswith("."):
            raise ValueError(f"invalid tenant name {tenant!r}")
        return self.model_dir(name) / "adapters" / tenant

    def adapter_path(self, name: str, tenant: str, version: int) -> Path:
        return self.adapter_dir(name, tenant) / f"v{int(version)}.zip"

    def tenants(self, name: str) -> List[str]:
        d = self.model_dir(name) / "adapters"
        if not d.exists():
            return []
        return sorted(t.name for t in d.iterdir()
                      if t.is_dir() and not t.name.startswith(".")
                      and self.adapter_versions(name, t.name))

    def adapter_versions(self, name: str, tenant: str) -> List[int]:
        d = self.adapter_dir(name, tenant)
        if not d.exists():
            return []
        return sorted(v for v in (_version_of(p) for p in d.iterdir())
                      if v is not None)

    def latest_adapter(self, name: str, tenant: str) -> Optional[int]:
        vs = self.adapter_versions(name, tenant)
        return vs[-1] if vs else None

    def publish_adapter(self, name: str, tenant: str, adapter: dict, *,
                        base_version: int, rank: int, alpha: float,
                        version: Optional[int] = None,
                        extra_meta: Optional[dict] = None) -> int:
        """Publish a tenant's adapter tree against a pinned
        `base_version` of `name` — the artifact is the DELTA alone
        (kilobytes), never a model zip. Returns the adapter version
        committed; `rank`/`alpha`/`base_version` ride meta.json so
        `resolve_adapter` can compose without side-channel state."""
        from deeplearning4j_tpu.tenancy import lora
        d = self.adapter_dir(name, tenant)
        d.mkdir(parents=True, exist_ok=True)
        tmp = d / f".publish-{os.getpid()}-{uuid.uuid4().hex[:8]}.tmp.zip"
        meta = dict(extra_meta or {})
        meta.update(model=name, tenant=tenant,
                    base_version=int(base_version), rank=int(rank),
                    alpha=float(alpha))
        try:
            lora.save_adapter(tmp, adapter, meta=meta)
            if version is not None:
                committed = self._claim_at(tmp, self.adapter_path(
                    name, tenant, int(version)), int(version))
                if committed is None:
                    raise VersionConflictError(
                        f"{name}/{tenant} adapter v{version} already "
                        f"exists — a concurrent publish won the claim")
            else:
                while True:
                    nxt = (self.latest_adapter(name, tenant) or 0) + 1
                    committed = self._claim_at(
                        tmp, self.adapter_path(name, tenant, nxt), nxt)
                    if committed is not None:
                        break
        finally:
            if tmp.exists():
                tmp.unlink()
        self._fsync_dir(d)
        from deeplearning4j_tpu import monitor
        if monitor.is_enabled():
            monitor.registry().counter(
                "registry_adapter_published_total",
                help="tenant adapter versions published",
                model=name, tenant=tenant).inc()
        self._gc_adapters(name, tenant)
        GLOBAL_FLIGHT_RECORDER.record("publish_adapter", model=name,
                                      tenant=tenant, version=committed,
                                      base_version=int(base_version))
        log.info("published adapter %s/%s v%d (base v%d) -> %s",
                 name, tenant, committed, base_version,
                 self.adapter_path(name, tenant, committed))
        return committed

    @staticmethod
    def _claim_at(tmp: Path, final: Path, version: int) -> Optional[int]:
        try:
            os.link(tmp, final)
            return version
        except FileExistsError:
            return None

    def resolve_adapter(self, name: str, tenant: str,
                        version: Union[int, str] = "latest"):
        """-> (adapter_tree, meta, version). `"latest"` walks
        newest-first with corrupt-artifact fallback (the model-store
        semantics); an explicit version fails hard on damage."""
        vs = self.adapter_versions(name, tenant)
        if not vs:
            raise FileNotFoundError(
                f"no published adapters for {name!r} tenant {tenant!r} "
                f"under {self.root} (known tenants: {self.tenants(name)})")
        from deeplearning4j_tpu.tenancy import lora
        if version != "latest":
            v = int(version)
            if v not in vs:
                raise FileNotFoundError(
                    f"{name}/{tenant} adapter v{v} is not in the "
                    f"registry (have {vs})")
            adapter, meta = lora.load_adapter(
                self.adapter_path(name, tenant, v))
            return adapter, meta, v
        m = self._metrics()
        tried = []
        for v in reversed(vs):
            try:
                adapter, meta = lora.load_adapter(
                    self.adapter_path(name, tenant, v))
                return adapter, meta, v
            except (ValueError, KeyError, OSError) as e:
                log.warning("%s/%s adapter v%d is corrupt (%s); "
                            "falling back", name, tenant, v, e)
                if m is not None:
                    m["fallback"].inc()
                tried.append((v, e))
        detail = "; ".join(f"v{v}: {e}" for v, e in tried)
        raise CheckpointCorruptError(
            f"every published adapter of {name!r}/{tenant!r} failed "
            f"verification ({len(tried)} candidates tried) — {detail}")

    def _adapter_pin_marker(self, name: str, tenant: str,
                            version: int) -> Path:
        return self.adapter_dir(name, tenant) / \
            f".pin-v{int(version)}.{os.getpid()}"

    def pin_adapter(self, name: str, tenant: str, version: int):
        """Protect a served adapter version from retention GC — the
        TenantFleet pins what each tenant is decoding with, exactly
        like the model-store pin (in-memory + on-disk marker)."""
        with self._lock:
            self._adapter_pinned.add((name, tenant, int(version)))
        d = self.adapter_dir(name, tenant)
        d.mkdir(parents=True, exist_ok=True)
        try:
            self._adapter_pin_marker(name, tenant, version).touch()
        except OSError:
            pass

    def unpin_adapter(self, name: str, tenant: str, version: int):
        with self._lock:
            self._adapter_pinned.discard((name, tenant, int(version)))
        try:
            self._adapter_pin_marker(name, tenant, version).unlink()
        except OSError:
            pass
        self._gc_adapters(name, tenant)

    def _adapter_marker_pins(self, name: str, tenant: str) -> Set[int]:
        import re
        keep: Set[int] = set()
        d = self.adapter_dir(name, tenant)
        if not d.exists():
            return keep
        for p in d.glob(".pin-v*.*"):
            mm = re.fullmatch(r"\.pin-v(\d+)\.(\d+)", p.name)
            if not mm:
                continue
            v, pid = int(mm.group(1)), int(mm.group(2))
            if pid == os.getpid() or self._pid_alive(pid):
                keep.add(v)
            else:
                try:
                    p.unlink()
                except OSError:
                    pass
        return keep

    def _gc_adapters(self, name: str, tenant: str):
        vs = self.adapter_versions(name, tenant)
        keep = set(vs[-self.keep_last:])
        if self.keep_every:
            keep.update(v for v in vs if v % self.keep_every == 0)
        with self._lock:
            keep.update(v for n, t, v in self._adapter_pinned
                        if n == name and t == tenant)
        keep.update(self._adapter_marker_pins(name, tenant))
        m = self._metrics()
        dropped = 0
        for v in vs:
            if v in keep:
                continue
            try:
                self.adapter_path(name, tenant, v).unlink()
                dropped += 1
                log.info("retention GC dropped adapter %s/%s v%d",
                         name, tenant, v)
            except OSError:
                pass
        if dropped and m is not None:
            m["gc"].inc(dropped)

    def adapter_publish_listener(self, name: str, tenant: str, *,
                                 base_version: int, rank: int,
                                 alpha: float, frequency: int = 100,
                                 every_s: Optional[float] = None,
                                 publish_at_fit_end: bool = True,
                                 gate=None):
        """The adapter-delta twin of `publish_listener`: every cadence
        boundary ships `tenancy.lora.extract_adapter(net)` via
        `publish_adapter` — kilobytes per release instead of a model
        zip, same step-boundary discipline and drift-gate semantics."""
        return AdapterPublishListener(
            self, name, tenant, base_version=base_version, rank=rank,
            alpha=alpha, frequency=frequency, every_s=every_s,
            publish_at_fit_end=publish_at_fit_end, gate=gate)

    # -------------------------------------------------- publish listener
    def publish_listener(self, name: str, *, frequency: int = 100,
                         epoch_frequency: Optional[int] = None,
                         every_s: Optional[float] = None,
                         save_updater: bool = False,
                         publish_at_fit_end: bool = True,
                         gate=None, normalizer_provider=None):
        """A TrainingListener that publishes the training model into
        this registry every `frequency` completed steps — checkpoint-
        as-publish as a one-liner:

            net.add_listener(registry.publish_listener("lm", frequency=500))

        `gate`: callable → bool consulted before every publish (the
        drift gate of `online/trainer.py`): False skips the publish
        WITHOUT advancing the cadence clock, so the next legal step
        boundary after the gate reopens publishes immediately — pause
        publishing, never training. `normalizer_provider`: callable →
        normalizer-or-None evaluated AT publish time (a
        `WindowedStandardize.snapshot` bound method), so each release
        carries the statistics of its own training window.

        `every_s`: WALL-CLOCK cadence alongside the step cadence — "a
        fresh model every N seconds regardless of throughput", the
        freshness promise a production fleet actually makes. A step
        boundary publishes when EITHER cadence is due; a slow stream
        (few steps per wall-second) publishes on the clock, a fast one
        on the step count. The clock anchors at fit start (a
        warm-started run owes a full period) and only advances on an
        ACTUAL publish — a gate refusal freezes it exactly like the
        step clock, so recovery publishes at the first legal
        boundary."""
        return RegistryPublishListener(
            self, name, frequency=frequency,
            epoch_frequency=epoch_frequency, every_s=every_s,
            save_updater=save_updater,
            publish_at_fit_end=publish_at_fit_end, gate=gate,
            normalizer_provider=normalizer_provider)


class RegistryPublishListener(TrainingListener):
    """Periodic publish from inside a fit loop — the CheckpointListener
    cadence discipline (fault/listener.py): only capture at
    ``step_boundary`` callbacks (a fused multi-step group's mid-group
    replays see post-group params with a mid-group iteration count —
    publishing there would serve a params/counter mismatch), and count
    "`frequency` steps since the last publish" rather than a modulo so
    misaligned boundaries publish at the next legal one."""

    def __init__(self, registry: ModelRegistry, name: str, *,
                 frequency: int = 100,
                 epoch_frequency: Optional[int] = None,
                 every_s: Optional[float] = None,
                 save_updater: bool = False,
                 publish_at_fit_end: bool = True,
                 gate=None, normalizer_provider=None):
        self.registry = registry
        self.name = name
        self.frequency = max(1, int(frequency))
        self.epoch_frequency = epoch_frequency
        if every_s is not None and float(every_s) <= 0:
            raise ValueError(f"every_s must be > 0; got {every_s}")
        self.every_s = None if every_s is None else float(every_s)
        self.save_updater = save_updater
        self.publish_at_fit_end = publish_at_fit_end
        self.gate = gate
        self.normalizer_provider = normalizer_provider
        self._last_published_step = 0
        self._last_published_time: Optional[float] = None
        self._last_gated_log_step = 0
        self._anchored = False
        self.published_versions: List[int] = []
        self.published_steps: List[int] = []
        self.gated_skips = 0

    def on_fit_start(self, model):
        # anchor the cadence clock at the CURRENT counter once: a
        # warm-started / resumed model (iteration_count >> 0) must wait
        # a full `frequency` of NEW steps for its first publish, not
        # publish immediately because the clock still reads 0
        if not self._anchored:
            self._anchored = True
            self._last_published_step = max(
                self._last_published_step, int(model.iteration_count))
            if self.every_s is not None:
                import time
                self._last_published_time = time.monotonic()

    def _clock_due(self) -> bool:
        """True when `every_s` wall-clock seconds passed since the
        last publish (or the fit-start anchor). Without an anchor yet
        (a listener driven outside a fit loop), the first boundary
        anchors the clock instead of publishing — the warm-start
        discipline applied to time."""
        if self.every_s is None:
            return False
        import time
        now = time.monotonic()
        if self._last_published_time is None:
            self._last_published_time = now
            return False
        return now - self._last_published_time >= self.every_s

    def _gated(self, step: int, *, windowed: bool = True) -> bool:
        """True when the gate currently refuses publishing. The
        cadence clock does NOT advance on a refusal — publishing
        resumes at the first legal boundary after recovery.

        The skip COUNT advances once per cadence WINDOW on the
        iteration path (`windowed=True`): while the gate stays closed
        every step boundary re-enters here (the frozen clock keeps the
        publish overdue), and counting each would over-report one
        refused release as `frequency` refusals. Epoch-end / fit-end
        refusals are discrete events (`windowed=False`) and count once
        per step."""
        if self.gate is None or self.gate():
            return False
        since = step - max(self._last_published_step,
                           self._last_gated_log_step)
        if (since >= self.frequency if windowed
                else step > self._last_gated_log_step):
            self._last_gated_log_step = step
            self.gated_skips += 1
            from deeplearning4j_tpu import monitor
            if monitor.is_enabled():
                monitor.registry().counter(
                    "online_publishes_skipped_total",
                    help="publishes refused by the drift gate (one "
                         "per refused cadence window / fit boundary)",
                    model=self.name).inc()
        return True

    def _publish(self, model, step: int):
        normalizer = (self.normalizer_provider()
                      if self.normalizer_provider is not None else None)
        v = self.registry.publish(self.name, model,
                                  save_updater=self.save_updater,
                                  normalizer=normalizer)
        self.published_versions.append(v)
        self.published_steps.append(step)
        self._last_published_step = step
        if self.every_s is not None:
            import time
            self._last_published_time = time.monotonic()
        from deeplearning4j_tpu import monitor
        if monitor.is_enabled():
            monitor.registry().counter(
                "online_publishes_total",
                help="model snapshots published into the serving "
                     "registry from a training loop",
                model=self.name).inc()

    def iteration_done(self, model, iteration, epoch, score, **info):
        if not info.get("step_boundary", True):
            return
        step = iteration + 1
        due_steps = step - self._last_published_step >= self.frequency
        if not due_steps and not self._clock_due():
            return
        if self._gated(step):
            return
        self._publish(model, step)

    def on_epoch_end(self, model, epoch):
        if (self.epoch_frequency
                and (epoch + 1) % self.epoch_frequency == 0
                and not self._gated(int(model.iteration_count),
                                    windowed=False)):
            self._publish(model, int(model.iteration_count))

    def on_fit_end(self, model):
        # online runs stop at arbitrary steps: the final snapshot
        # publishes even when the stop iteration is off-cadence (the
        # drift gate still applies — a degraded final model must not
        # ship just because the stream ended while it was degraded)
        if self.publish_at_fit_end and \
                int(model.iteration_count) > self._last_published_step \
                and not self._gated(int(model.iteration_count),
                                    windowed=False):
            self._publish(model, int(model.iteration_count))


class AdapterPublishListener(RegistryPublishListener):
    """RegistryPublishListener whose publish unit is the tenant's
    adapter DELTA (`tenancy.lora.extract_adapter`) against a pinned
    base version — all cadence/gate/step-boundary semantics inherited;
    only what ships changes."""

    def __init__(self, registry: ModelRegistry, name: str, tenant: str,
                 *, base_version: int, rank: int, alpha: float,
                 frequency: int = 100, every_s: Optional[float] = None,
                 publish_at_fit_end: bool = True, gate=None):
        super().__init__(registry, name, frequency=frequency,
                         every_s=every_s,
                         publish_at_fit_end=publish_at_fit_end,
                         gate=gate)
        self.tenant = tenant
        self.base_version = int(base_version)
        self.rank = int(rank)
        self.alpha = float(alpha)

    def _publish(self, model, step: int):
        from deeplearning4j_tpu.tenancy import lora
        adapter = lora.extract_adapter(model)
        if not adapter:
            raise ValueError(
                f"model for {self.name}/{self.tenant} carries no "
                f"attached adapter — lora.attach_adapter() before fit")
        v = self.registry.publish_adapter(
            self.name, self.tenant, adapter,
            base_version=self.base_version, rank=self.rank,
            alpha=self.alpha, extra_meta={"step": step})
        self.published_versions.append(v)
        self.published_steps.append(step)
        self._last_published_step = step
        if self.every_s is not None:
            import time
            self._last_published_time = time.monotonic()
        from deeplearning4j_tpu import monitor
        if monitor.is_enabled():
            monitor.registry().counter(
                "online_adapter_publishes_total",
                help="adapter deltas published into the serving "
                     "registry from a tenant's training loop",
                model=self.name, tenant=self.tenant).inc()
