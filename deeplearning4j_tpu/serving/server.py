"""Continuous-batching generation server.

The request plane of the serving tier (the device programs live in
serving/engine.py): `GenerationServer` EXTENDS `ParallelInference` —
same request queue, Future resolution, start/stop/shutdown lifecycle
and drain-on-teardown semantics — but replaces the coalesce-one-batch
collector with a continuous-batching scheduler: every loop iteration
admits newly queued prompts into free slots (prefill), advances ALL
active slots one token (one jitted dispatch), streams the new tokens
out per request, and retires finished/cancelled sequences so their
pool blocks serve the next admission. A single long generation no
longer blocks the batch — this is what TF-Serving's async batching
added on top of the TF runtime (PAPERS.md §serving), rebuilt over a
paged KV pool.

SLO-aware shedding: with `slo_ttft_s` set, a request whose PROJECTED
queue delay (outstanding decode work / measured token throughput)
exceeds the SLO is fast-failed with `ShedError` at admission time
instead of queueing into certain lateness; `max_queue` is the hard
backstop when no throughput estimate exists yet. Both fire the
`serving_shed_total` counter — the registry is the signal plane
(docs/OBSERVABILITY.md "Serving").
"""

from __future__ import annotations

import os
import queue
import threading
import time
from typing import Iterator, List, Optional

import numpy as np

from deeplearning4j_tpu import monitor
from deeplearning4j_tpu.monitor.flightrec import GLOBAL_FLIGHT_RECORDER
from deeplearning4j_tpu.monitor.goodput import (
    GOODPUT_COUNTER_FAMILIES, GOODPUT_FRACTION_GAUGE, ttft_decomposition)
from deeplearning4j_tpu.monitor.reqtrace import RequestTrace
from deeplearning4j_tpu.monitor.slo import SLOObjective, SLOTracker
from deeplearning4j_tpu.parallel.inference import ParallelInference
from deeplearning4j_tpu.serving.engine import PagedDecodeEngine
from deeplearning4j_tpu.serving.paged import blocks_needed

_DONE = object()


class ShedError(RuntimeError):
    """Request fast-failed by the SLO admission policy (shed, not
    queued): retry against another replica or with backoff."""


class ServerDrainingError(RuntimeError):
    """Admission refused because the server is draining (`drain()` —
    the hot-swap handoff): in-flight streams finish, new requests
    belong on the successor. A `FleetRouter` retries against the
    freshly-resolved active server; direct callers should re-resolve."""


class ServerStoppedError(RuntimeError):
    """`start()` after `stop()`: a stopped GenerationServer's engine
    has failed its in-flight streams and retired their slots —
    restarting the scheduler over that state would corrupt the
    allocator bookkeeping. Build a fresh server instead."""


class TokenStream:
    """Per-request token stream: iterate for tokens as they decode, or
    block on `result()` for the full array (the Future face —
    `ParallelInference.output_async` compatibility)."""

    def __init__(self, fut, prompt_len: int, n_tokens: int,
                 on_close=None):
        self._fut = fut
        self._q: "queue.Queue" = queue.Queue()
        self.prompt_len = prompt_len
        self.n_tokens = n_tokens
        self.tokens: List[int] = []
        self.cancelled = False
        # per-request lifecycle trace (None when monitoring is off and
        # no upstream trace context arrived): the scheduler stamps
        # phases onto it; finish/fail seal it
        self.trace: Optional[RequestTrace] = None
        self.t_submit = time.monotonic()
        self.t_first: Optional[float] = None
        self.t_last: Optional[float] = None
        # close hook (fires exactly once, on finish OR failure): the
        # server's open-stream accounting — what makes drain() a
        # zero-dropped-streams barrier instead of a scheduler-state
        # guess (a request between queue.get and _pending.append is
        # visible nowhere else)
        self._on_close = on_close
        self._closed = False

    # ------------------------------------------------------------ consumer
    def __iter__(self) -> Iterator[int]:
        while True:
            item = self._q.get()
            if item is _DONE:
                # surface a shed/teardown error to iterating consumers
                # too, not only result() callers
                exc = self._fut.exception(timeout=0)
                if exc is not None and not self.cancelled:
                    raise exc
                return
            # tokens arrive in per-dispatch batches: one queue wakeup
            # per CHUNK, not per token — with many iterating consumer
            # threads, per-token wakeups were measured to collapse
            # aggregate throughput ~20x (GIL convoy against the
            # scheduler thread)
            yield from item

    def result(self, timeout: Optional[float] = None) -> np.ndarray:
        """Full generated-id array [n_emitted]; raises ShedError /
        teardown errors like a Future."""
        return self._fut.result(timeout)

    def cancel(self):
        """Evict this request mid-stream: the scheduler frees its slot
        and pool blocks at the next loop iteration; `result()` resolves
        with the tokens emitted so far."""
        self.cancelled = True

    # ----------------------------------------------------------- producer
    def _emit(self, token: int, now: float):
        self._emit_many([token], now)

    def _emit_many(self, toks, now: float):
        if not toks:
            return
        if self.t_first is None:
            self.t_first = now
        self.t_last = now
        toks = [int(t) for t in toks]
        self.tokens.extend(toks)
        self._q.put(toks)

    def _close(self):
        if self._closed:
            return
        self._closed = True
        if self._on_close is not None:
            self._on_close()

    def _finish(self):
        if not self._fut.done():
            self._fut.set_result(np.asarray(self.tokens, np.int32))
        self._q.put(_DONE)
        if self.trace is not None:
            # idempotent: the scheduler's richer finish (ttft/slo args)
            # already sealed it on the normal path
            self.trace.finish(
                status="cancelled" if self.cancelled else "ok",
                tokens=len(self.tokens))
        self._close()

    def _fail(self, exc: BaseException):
        if not self._fut.done():
            self._fut.set_exception(exc)
        self._q.put(_DONE)
        if self.trace is not None:
            self.trace.finish(
                status="shed" if isinstance(exc, ShedError) else "error",
                error=type(exc).__name__)
        self._close()


class _Request:
    __slots__ = ("prompt", "n_tokens", "temperature", "top_p", "rng",
                 "stream", "slot", "emit_base")

    def __init__(self, prompt, n_tokens, temperature, top_p, rng, stream,
                 emit_base: int = 0):
        self.prompt = prompt
        self.n_tokens = n_tokens
        self.temperature = temperature
        self.top_p = top_p
        self.rng = rng
        self.stream = stream
        self.slot = None
        # rng fold offset carried in from OUTSIDE this server: a
        # cross-replica continuation (migration after a replica died
        # mid-stream) arrives as prompt+received with emit_start =
        # tokens already emitted elsewhere — sampling must keep folding
        # at the original stream's positions, not restart at 0
        self.emit_base = int(emit_base)

    # ---- preempt-and-requeue continuation (incremental allocation):
    # a pool-pressure eviction re-admits the request as its original
    # prompt EXTENDED by every token already streamed, generating only
    # the remainder — greedy continuations are bit-consistent (prefill
    # of the extended prompt reproduces the decode-path numerics, the
    # parity contract) and sampled ones keep their fold_in(key, t)
    # indices via emit_start.
    def effective_prompt(self):
        import numpy as np
        done = self.stream.tokens
        if not done:
            return self.prompt
        return np.concatenate(
            [self.prompt, np.asarray(done, self.prompt.dtype)])

    @property
    def emitted(self) -> int:
        return len(self.stream.tokens)

    @property
    def n_left(self) -> int:
        return self.n_tokens - self.emitted


class GenerationServer(ParallelInference):
    """Continuous-batching autoregressive serving over a paged KV pool.

    `generate_async(prompt, n_tokens) -> TokenStream` from any thread;
    the scheduler thread (started by `start()`, the inherited
    lifecycle) owns the engine. `top_k` is server-static (one XLA
    decode program); temperature/top_p/rng are per-request.
    """

    def __init__(self, net, *, n_slots: int = 8, n_blocks: int = 64,
                 block_len: int = 16, top_k: Optional[int] = None,
                 steps_per_dispatch: int = 1,
                 slo_ttft_s: Optional[float] = None,
                 max_queue: Optional[int] = None,
                 idle_wait_s: float = 0.05,
                 dispatch_floor_s: Optional[float] = None,
                 quantize: Optional[str] = None,
                 allocation: str = "incremental",
                 speculative: Optional[int] = None,
                 spec_accept_floor: float = 0.3,
                 spec_probe_every: int = 50,
                 spec_sampled: bool = False,
                 spec_draft_layers: Optional[int] = None,
                 prefix_cache: str = "registered",
                 name: Optional[str] = None,
                 slo: Optional[SLOObjective] = None):
        super().__init__(net)
        # optional server label: `serving_*` families carry
        # `server=<name>` so two servers in one process (a fleet) don't
        # collide; the single-server path stays unlabeled (PR-12 note)
        self.name = name
        # optional SLO objective: good/bad counters + burn-rate gauge
        # evaluated per finished request (shed counts as bad)
        self._slo_tracker = (SLOTracker(slo, model=name or "default")
                            if slo is not None else None)
        self._slo_cache = None
        # shed-burst flight-recorder rate limit (≤1 event/s)
        self._shed_recent = 0
        self._shed_last_emit = 0.0
        self.engine = PagedDecodeEngine(
            net, n_slots=n_slots, n_blocks=n_blocks, block_len=block_len,
            top_k=top_k, steps_per_dispatch=steps_per_dispatch,
            quantize=quantize, allocation=allocation,
            speculative=speculative, spec_sampled=spec_sampled,
            spec_draft_layers=spec_draft_layers,
            prefix_cache=prefix_cache)
        self._metrics_cache = None
        # speculative-decoding policy: drafting is only worth its
        # k-wide scoring dispatch while the proposer's tokens actually
        # get accepted — the scheduler tracks an acceptance-rate EWMA
        # and falls back to the chunked decode program when it sinks
        # below `spec_accept_floor`, re-probing one speculative
        # dispatch every `spec_probe_every` dispatches so a workload
        # shift (e.g. traffic turning repetitive again) re-enables it
        self.spec_accept_floor = float(spec_accept_floor)
        self.spec_probe_every = max(1, int(spec_probe_every))
        self._spec_accept_ewma: Optional[float] = None
        self._spec_tpd_ewma: Optional[float] = None
        self._spec_disabled = False
        self._spec_probe_in = 0
        self._spec_proposed_seen = 0
        self._spec_accepted_seen = 0
        self._spec_emitted_seen = 0
        self._spec_dispatches_seen = 0
        # per-proposer arbitration: separate acceptance EWMAs so a
        # collapsed n-gram cache (non-repetitive traffic) hands the
        # drafting seam to the truncated-layer backend instead of
        # disabling speculation outright; the global EWMA/latch above
        # stays authoritative for the enable/disable decision
        self._spec_prop_ewma = {"ngram": None, "truncated": None}
        self._spec_prop_seen = {"ngram": (0, 0), "truncated": (0, 0)}
        self._prefix_hits_seen = 0
        self._prefix_saved_seen = 0
        # radix-cache counter mirrors (radix mode only)
        self._radix_hits_seen = 0
        self._radix_evict_seen = 0
        # goodput-ledger mirror cursors (one per classification class)
        self._goodput_seen = {}
        # prefix registrations from foreign threads ride a control
        # queue the scheduler drains at each loop top (the engine is
        # single-threaded by contract); before start() they apply
        # directly
        self._control: "queue.Queue" = queue.Queue()
        self.slo_ttft_s = slo_ttft_s
        self.max_queue = max_queue
        self.idle_wait_s = idle_wait_s
        # emulated device-step latency floor (sandbox/test seam): each
        # decode dispatch takes at least this long, with the host
        # sleeping out the remainder as if the accelerator owned the
        # step. On a CPU-only sandbox this reproduces the device-bound
        # serving regime (host idle inside the step) that replica
        # fan-out and SLO tests are really about — it must never be
        # set in production serving, so setting it requires the
        # explicit sandbox opt-in (DL4J_SANDBOX_MODEL=1): a copied
        # loadtest config can otherwise silently cap a production
        # server's throughput at 1/dispatch_floor_s dispatches/s.
        if dispatch_floor_s is not None \
                and os.environ.get("DL4J_SANDBOX_MODEL") != "1":
            raise ValueError(
                "dispatch_floor_s emulates device-step latency and is "
                "a sandbox-only seam — it must never be set in "
                "production serving. Set DL4J_SANDBOX_MODEL=1 to "
                "acknowledge this is a sandbox/loadtest process.")
        self.dispatch_floor_s = (None if dispatch_floor_s is None
                                 else float(dispatch_floor_s))
        self._pending: List = []          # admission order, after _queue
        self._slot2req = {}
        # shedding estimator: EWMA of aggregate decode throughput
        self._ewma_tok_s: Optional[float] = None
        # counter mirrors: the engine keeps host ints (it has no
        # registry); the scheduler publishes the deltas each loop
        self._grants_seen = 0
        self._requeue_seen = 0
        # lifecycle: draining refuses admissions while in-flight
        # streams finish (the hot-swap handoff); stopped is terminal
        self._draining = False
        self._stopped = False
        self._open_streams = 0
        self._queued_tokens = 0
        self._open_lock = threading.Lock()

    # ---------------------------------------------------- open-stream book
    def _stream_closed(self):
        with self._open_lock:
            self._open_streams -= 1

    @property
    def open_streams(self) -> int:
        """Streams submitted and not yet finished/failed — counted at
        the TokenStream close hook, so a request is visible here from
        `generate_async` until its future resolves (including the
        scheduler-internal limbo between queue and pending list)."""
        with self._open_lock:
            return self._open_streams

    @property
    def queued_tokens(self) -> int:
        """Tokens owed by requests still in the SUBMIT queue (not yet
        taken by the scheduler): a running counter — incremented at
        `generate_async`, decremented when the scheduler (or teardown)
        takes the item — so an external projected-delay estimator (the
        FleetRouter) reads it O(1) instead of copying the queue under
        its mutex on every submit."""
        with self._open_lock:
            return max(0, self._queued_tokens)

    def queue_depth(self) -> int:
        """Requests awaiting admission: the submit queue plus the
        scheduler's pending list — the same value the
        `serving_queue_depth` gauge publishes, as a public seam so the
        autoscaler's live fallback and the router's shed estimator
        don't reach into scheduler internals. Lock-free reads of two
        thread-safe sizes; may be one scheduler iteration stale."""
        return len(self._pending) + self._queue.qsize()

    def _queue_item_taken(self, item):
        """Bookkeeping for every item removed from `_queue` (None
        sentinels excluded — they were never counted)."""
        if item is None:
            return
        with self._open_lock:
            self._queued_tokens -= int(getattr(item[0], "n_tokens", 0))

    def output_async(self, x):
        """Not supported here: the scheduler queue carries generation
        requests, not raw feature batches — a ParallelInference-style
        enqueue would poison the scheduler loop. Use `generate_async`
        (token streams) or a separate `ParallelInference` for
        single-shot forwards."""
        raise NotImplementedError(
            "GenerationServer serves token streams: use "
            "generate_async(prompt_ids, n_tokens); for single-shot "
            "batched forwards use ParallelInference")

    # ------------------------------------------------------ shared prefix
    def register_prefix(self, token_ids, *, timeout: Optional[float] = 600.0
                        ) -> tuple:
        """Warm a shared prompt prefix (system prompt) into the paged
        pool ONCE: later requests whose prompt starts with these ids
        map the warmed blocks copy-on-write instead of re-prefilling
        them (`PagedDecodeEngine.register_prefix`; docs/SERVING.md).
        Thread-safe: before `start()` the registration applies
        directly (the usual deploy order — register, `warmup()`,
        `start()` — so warmup can pre-compile the suffix-extension
        programs); on a RUNNING server it rides a control queue the
        scheduler drains, and this call blocks until applied."""
        if getattr(self, "_shutdown", False) or self._stopped:
            raise RuntimeError("GenerationServer is shut down")
        if not self._running:
            return self.engine.register_prefix(token_ids)
        from concurrent.futures import Future
        fut = Future()
        self._control.put(("register_prefix", token_ids, fut))
        # re-check teardown AFTER the put: a stop() landing between the
        # checks above and the enqueue has already drained the control
        # queue — our item would sit unresolved forever. Draining once
        # more here races benignly with the scheduler (get_nowait on
        # both sides) and guarantees the future resolves either way.
        if self._stopped or getattr(self, "_shutdown", False) \
                or not self._running:
            self._fail_control()
        return fut.result(timeout)

    def _drain_control(self, eng) -> bool:
        progressed = False
        while True:
            try:
                op, arg, fut = self._control.get_nowait()
            except queue.Empty:
                return progressed
            progressed = True
            try:
                if op == "register_prefix":
                    fut.set_result(eng.register_prefix(arg))
                else:
                    raise ValueError(f"unknown control op {op!r}")
            except Exception as e:  # noqa: BLE001 — surfaced to caller
                if not fut.done():
                    fut.set_exception(e)

    # ------------------------------------------------------------- warmup
    def warmup(self, prompt_len: int, n_tokens: int = 2):
        """Compile the serving programs OUTSIDE the serving path: the
        full (wave-width-pow2 x prompt-length-bucket) program grid up
        to the slot count and `bucket_len(prompt_len)` — async arrival
        means real waves take EVERY quantized width, mixed-length
        traffic takes every length bucket, and each (width, bucket)
        pair is its own batched-prefill program (the admit_finish and
        decode programs key on width alone). Call BEFORE start() — an
        XLA compile inside a live admission wave stalls every queued
        request behind ~seconds of tracing (measured as a p50==p99
        TTFT cliff on the CPU sandbox; stack sampling showed the
        scheduler thread pinned in backend_compile)."""
        from deeplearning4j_tpu.serving.engine import bucket_len
        if self._running:
            raise RuntimeError("warmup() must run before start()")
        # persistent XLA compile cache (DL4J_COMPILE_CACHE_DIR): a
        # fleet successor re-warming the same (width x bucket) grid
        # loads executables from disk instead of re-tracing them —
        # near-instant swap warmup on revisited configurations
        from deeplearning4j_tpu.nd.compile_cache import enable_compile_cache
        enable_compile_cache()
        eng = self.engine
        n_tokens = max(2, int(n_tokens))
        self.engine.check_budget(int(prompt_len), n_tokens)
        widths = []
        w = 1
        while w < eng.n_slots:
            widths.append(w)
            w *= 2
        widths.append(eng.n_slots)
        top_bucket = bucket_len(int(prompt_len), eng.max_total_tokens)
        buckets = []
        b = 1
        while b <= top_bucket:
            buckets.append(b)
            b *= 2
        if buckets[-1] != top_bucket:
            buckets.append(top_bucket)     # budget-clamped odd bucket
        # each (width, bucket) warms BOTH admit variants (all-greedy
        # and the sampling chain) — a mixed wave keys a different
        # program — and the first sampled wave also compiles the
        # sampled decode chunk, so a temperature>0 request never
        # stalls live streams on a mid-serving trace. Prefix matching
        # is suspended for the grid: a registered prefix that happens
        # to match the synthetic zero prompts would route these waves
        # through the CoW path and leave the REAL full-prefill
        # programs cold for live traffic of that shape.
        saved_prefixes, eng._prefixes = eng._prefixes, {}
        # the radix cache is suspended for the same reason — and so the
        # grid's synthetic zero prompts don't seed the tree with
        # garbage-content nodes real traffic would then "hit"
        saved_radix, eng._radix = eng._radix, None
        short_wave = None      # narrowest under-admitted wave seen
        # goodput: everything the compile grid dispatches is warmup
        # class — the ledger stays monotone (no counter reset here, so
        # registry mirrors never see negative deltas) while the useful
        # fraction keeps counting real traffic only
        eng.goodput.set_mode("warmup")
        try:
            for k in widths:
                for pl in buckets:
                    # a bucket rounded past the prompt may leave less
                    # token headroom than requested — admission-only
                    # warmup (n=1) still compiles that bucket's
                    # prefill/admit programs
                    pw = int(pl)
                    n_b = min(n_tokens, eng.max_total_tokens - pw)
                    if n_b < 1:
                        # the budget-clamped TOP bucket: a one-shorter
                        # prompt still PADS to this bucket, so the same
                        # (width, bucket) prefill program compiles — a
                        # real budget-edge request must not be the first
                        # to trace it
                        pw, n_b = pw - 1, 1
                        if pw < 1:
                            continue
                    for sampled_head in (False, True):
                        reqs = [dict(prompt_ids=np.zeros(pw, np.int32),
                                     n_tokens=n_b)
                                for _ in range(k)]
                        if sampled_head:
                            reqs[0].update(temperature=1.0,
                                           rng=np.zeros(2, np.uint32))
                        admitted = eng.admit_many(reqs)
                        while eng.active.any():
                            # speculate=False: the grid warms the
                            # CHUNKED decode programs — the accept-rate
                            # fallback path must be as cold-start-free
                            # as the speculative one (warmed below)
                            eng.step(speculate=False)
                        eng.drain_preempted()  # warmup traffic isn't real
                        for slot, _, done in admitted:
                            if not done and eng.slots[slot] is not None:
                                eng.evict(slot)
                        if len(admitted) < k and short_wave is None:
                            short_wave = (len(admitted), k)
                if short_wave is not None:
                    # pool too small for this width (at SOME bucket)
                    # even at warmup's minimal n_tokens — real waves of
                    # this width compile mid-serving if requests ever
                    # need fewer blocks each
                    import logging
                    logging.getLogger(__name__).warning(
                        "warmup admitted only %d of a width-%d wave "
                        "(pool %d blocks): wave widths above %d are NOT "
                        "fully pre-compiled — grow n_blocks or expect a "
                        "one-off compile stall on the first wider wave",
                        short_wave[0], short_wave[1], eng.pool.n_blocks,
                        short_wave[0])
                    break
        finally:
            eng._prefixes = saved_prefixes
            eng._radix = saved_radix
            eng.goodput.set_mode(None)
        import jax.numpy as jnp
        # speculative + shared-prefix programs: the K-position score
        # program (both sampling variants), the CoW fork copy, and the
        # exact-match first-token sampler — compiled via DEAD dispatches
        # (n_valid all zero / garbage-to-garbage copies), which write
        # only the garbage block and leave every pool invariant intact
        score_ks = []
        if eng.spec_k:
            score_ks.append(eng.spec_k)
        if eng.has_prefixes or eng._radix is not None:
            # suffix-extension buckets: every pow2 up to the prompt
            # bucket (a hit's suffix is at most prompt minus prefix) —
            # radix hits ride the same suffix-extension score programs
            b = 1
            while b <= bucket_len(int(prompt_len), eng.max_total_tokens):
                score_ks.append(b)
                b *= 2
        S = eng.n_slots
        for K in sorted(set(score_ks)):
            variants = [True, False]
            if eng.spec_sampled and eng.spec_k and K == eng.spec_k:
                # rejection-sampling score variant (sampled streams)
                variants.append("rs")
            for variant in variants:
                score = eng._get_score(K, variant)
                eng.pool.kv = score(
                    eng._params, eng.net.net_state, eng.pool.kv,
                    jnp.asarray(eng.block_tables),
                    jnp.zeros((S, K), jnp.int32),
                    jnp.zeros(S, jnp.int32), jnp.zeros(S, jnp.int32),
                    jnp.zeros((S, 2), jnp.uint32),
                    jnp.zeros(S, jnp.int32), jnp.zeros(S, jnp.float32),
                    jnp.ones(S, jnp.float32))[0]
        if eng._draft_plan is not None:
            # truncated-layer draft program: a dead dispatch (every
            # table row garbage) compiles the k-1 micro-step scan
            eng.goodput.set_mode("warmup")
            try:
                eng._run_draft([])
            finally:
                eng.goodput.set_mode(None)
        if eng.has_prefixes:
            # fork widths up to a full wave of mid-block tails (every
            # admission in a wave can fork one) — garbage self-copies
            w = 1
            while True:
                self.engine._run_fork([(0, 0)] * w)
                if w >= S:
                    break
                w *= 2
            vocab = getattr(eng.net.layers[-1], "n_out", 0)
            # pow2 CEIL of the slot count (like the fork loop above):
            # a 5-wide exact-match wave on a 6-slot server pads to
            # width 8 — `while w <= S` would leave that width to
            # compile mid-serving, the TTFT-cliff class warmup exists
            # to prevent
            w = 1
            while True:
                for greedy in (True, False):
                    fn = eng._first_token.get(greedy)
                    if fn is None:
                        fn = eng._first_token[greedy] = \
                            eng._build_first_token(greedy)
                    fn(jnp.zeros((w, vocab),
                                 eng.net.dtype.compute_dtype),
                       jnp.zeros((w, 2), jnp.uint32),
                       jnp.zeros(w, jnp.int32),
                       jnp.zeros(w, jnp.float32), jnp.ones(w, jnp.float32))
                if w >= S:
                    break
                w *= 2
        # the warmup grid's grants/preemptions are not serving traffic:
        # reset the engine totals so the registry deltas (_drain) and
        # ledger reads count real requests only (prefix pins and their
        # hit/fork counters predate traffic too)
        eng.block_grants_total = 0
        eng.evict_requeue_total = 0
        eng.prefix_forks_total = 0
        eng.prefix_hits_total = 0
        eng.prefix_tokens_saved_total = 0
        eng.spec_draft_dispatches_total = 0
        eng.radix_hit_tokens_total = 0
        eng.radix_evictions_total = 0
        return self

    # ------------------------------------------------------------- submit
    def generate_async(self, prompt_ids, n_tokens: int, *,
                       temperature: float = 0.0,
                       top_p: Optional[float] = None,
                       rng=None, emit_start: int = 0,
                       trace: Optional[RequestTrace] = None) -> TokenStream:
        """Enqueue one generation request; returns its token stream.
        Eager validation (the `generate()` pattern): impossible
        requests fail HERE, not as a scheduler-thread error.

        `emit_start` is the continuation seam for CROSS-SERVER
        migration: a stream that died on another replica after K tokens
        resubmits as prompt+received with ``emit_start=K`` — greedy
        continuations are bit-consistent by the parity contract and
        sampled ones keep their fold_in(key, position) indices, so the
        joined stream equals the uninterrupted one.

        `trace` carries upstream trace context (a router-side
        RequestTrace or one rehydrated from the wire); with monitoring
        enabled and no upstream context, a fresh trace is minted here —
        trace-off serving emits the same tokens bit-for-bit (tracing is
        host-side timestamps only, it never touches rng or devices)."""
        if getattr(self, "_shutdown", False):
            raise RuntimeError("GenerationServer is shut down")
        if self._draining:
            raise ServerDrainingError(
                "GenerationServer is draining: in-flight streams are "
                "finishing but admissions are closed — submit to the "
                "successor (FleetRouter re-resolves automatically)")
        if not self._running:
            raise RuntimeError("call start() before generate_async()")
        prompt = np.asarray(prompt_ids)
        if prompt.ndim == 2 and prompt.shape[0] == 1:
            prompt = prompt[0]
        if prompt.ndim != 1 or prompt.size == 0:
            raise ValueError(f"prompt must be a non-empty 1-D id "
                             f"sequence; got shape {prompt.shape}")
        self.engine.check_budget(int(prompt.shape[0]), int(n_tokens),
                                 prompt_ids=prompt)
        if top_p is not None and not (0.0 < float(top_p) <= 1.0):
            raise ValueError(f"top_p must be in (0, 1]; got {top_p}")
        if temperature < 0:
            raise ValueError(f"temperature must be >= 0; got {temperature}")
        if temperature > 0 and rng is None:
            # every no-rng sampled request must draw a DISTINCT stream:
            # the engine's deterministic default (zero key) would make
            # concurrent same-prompt requests emit identical "samples".
            # Pass rng explicitly for a reproducible stream (the
            # fold-per-position contract, docs/SERVING.md).
            rng = np.frombuffer(os.urandom(8), np.uint32).copy()
        from concurrent.futures import Future
        fut = Future()
        stream = TokenStream(fut, int(prompt.shape[0]), int(n_tokens),
                             on_close=self._stream_closed)
        if trace is None and monitor.is_enabled():
            trace = RequestTrace(model=self.name)
        if trace is not None:
            trace.annotate(prompt_len=int(prompt.shape[0]),
                           n_tokens=int(n_tokens))
            if trace.model is None:
                trace.model = self.name
        stream.trace = trace
        with self._open_lock:
            # re-check the drain flag ATOMICALLY with the open-stream
            # increment: drain() sets the flag and reads the count
            # under this same lock, so a submit either increments
            # before drain reads (drain waits for it) or sees the flag
            # and raises — it can never slip a request into a server
            # drain already declared empty (the stream would hang
            # unserviced after the subsequent stop())
            if self._draining:
                raise ServerDrainingError(
                    "GenerationServer is draining: in-flight streams "
                    "are finishing but admissions are closed — submit "
                    "to the successor (FleetRouter re-resolves "
                    "automatically)")
            self._open_streams += 1
            self._queued_tokens += int(n_tokens)
        req = _Request(prompt.astype(np.int64), int(n_tokens),
                       float(temperature), top_p, rng, stream,
                       emit_base=int(emit_start))
        self._queue.put((req, fut, stream.t_submit))
        if getattr(self, "_shutdown", False):
            self._fail_pending()
        return stream

    # -------------------------------------------- queued-request migration
    def export_queued(self) -> List:
        """Take every QUEUED-BUT-UNSTARTED request out of the submit
        queue for migration to another server (the hot-swap successor,
        or a less-loaded replica). Only the submit queue is exported —
        requests the scheduler has already seen (pending list, live
        slots) have state on THIS server and finish here; a queued item
        has emitted nothing, so it moves wholesale. Thread-safe against
        a running scheduler: both sides drain the same thread-safe
        queue, so each item lands exactly once — here or in a slot,
        never both. Returns opaque items for `adopt_queued`."""
        items = []
        while True:
            try:
                item = self._queue.get_nowait()
            except queue.Empty:
                break
            self._queue_item_taken(item)
            if item is None:
                continue
            items.append(item)
        if items:
            # the streams remain OPEN (their consumers keep waiting) but
            # no longer this server's liability: drain() must not block
            # on requests another server now owes
            with self._open_lock:
                self._open_streams -= len(items)
        return items

    def adopt_queued(self, items) -> int:
        """Adopt requests exported from another server's queue: each
        stream object is re-owned wholesale — same TokenStream, same
        consumer-held future, new server on the hook for it (the close
        hook rebinds, so open-stream accounting follows the request).
        Returns the number adopted."""
        if not items:
            return 0
        if getattr(self, "_shutdown", False) or self._stopped:
            raise RuntimeError("GenerationServer is shut down")
        if self._draining:
            raise ServerDrainingError(
                "cannot adopt migrated requests into a draining server")
        for item in items:
            req = item[0]
            req.stream._on_close = self._stream_closed
            tr = req.stream.trace
            if tr is not None:
                tr.event("migrated", to=self.name)
            with self._open_lock:
                self._open_streams += 1
                self._queued_tokens += int(req.n_tokens)
            self._queue.put(item)
        return len(items)

    # ------------------------------------------------------------ metrics
    def _serving_metrics(self):
        return self._resolve_metrics("_metrics_cache",
                                     self._build_serving_metrics)

    def _build_serving_metrics(self, reg):
        # optional `server=` label (satellite of PR 16): two servers in
        # one process (the fleet path) get distinct children; a
        # name-less server keeps the original unlabeled series
        lbl = {"server": self.name} if self.name else {}
        fams = {
            "queue": reg.gauge("serving_queue_depth",
                               "generation requests awaiting admission",
                               **lbl),
            "slots": reg.gauge("serving_active_slots",
                               "serving slots decoding right now", **lbl),
            "blocks": reg.gauge("serving_free_blocks",
                                "free KV-pool blocks", **lbl),
            "requests": reg.counter("serving_requests_total",
                                    "generation requests admitted",
                                    **lbl),
            "tokens": reg.counter("serving_tokens_total",
                                  "tokens emitted by the decode loop",
                                  **lbl),
            "shed": reg.counter("serving_shed_total",
                                "requests fast-failed by the SLO "
                                "admission policy", **lbl),
            "evicted": reg.counter("serving_evicted_total",
                                   "sequences evicted mid-stream", **lbl),
            "pool_free": reg.gauge("serving_pool_blocks_free",
                                   "free KV-pool blocks (allocator "
                                   "view)", **lbl),
            "pool_used": reg.gauge("serving_pool_blocks_used",
                                   "granted KV-pool blocks", **lbl),
            "grants": reg.counter("serving_block_grants_total",
                                  "pool blocks granted (admission + "
                                  "lazy decode growth)", **lbl),
            "requeue": reg.counter("serving_evict_requeue_total",
                                   "pool-pressure preemptions requeued "
                                   "as continuations", **lbl),
            "spec_accept": reg.gauge(
                "serving_spec_accept_rate",
                "EWMA of the draft-token acceptance rate (speculative "
                "decoding; drives the auto-disable policy)", **lbl),
            "spec_tpd": reg.gauge(
                "serving_spec_tokens_per_dispatch",
                "EWMA of tokens emitted per speculative dispatch",
                **lbl),
            "prefix_shared": reg.gauge(
                "serving_prefix_blocks_shared",
                "pool blocks currently mapped by more than one holder "
                "(shared-prefix CoW)", **lbl),
            "prefix_hits": reg.counter(
                "serving_prefix_hits_total",
                "admissions that mapped a registered shared prefix "
                "instead of prefilling it", **lbl),
            "prefix_saved": reg.counter(
                "serving_prefix_tokens_saved_total",
                "prompt tokens NOT prefilled thanks to shared-prefix "
                "block reuse", **lbl),
            "spec_accept_by": {
                p: reg.gauge(
                    "serving_spec_accept_rate",
                    "EWMA of the draft-token acceptance rate (speculative "
                    "decoding; drives the auto-disable policy)",
                    proposer=p, **lbl)
                for p in ("ngram", "truncated")},
            "spec_proposed_by": {
                p: reg.counter(
                    "serving_spec_proposed_total",
                    "draft tokens offered to the verify dispatch",
                    proposer=p, **lbl)
                for p in ("ngram", "truncated")},
            "spec_accepted_by": {
                p: reg.counter(
                    "serving_spec_accepted_total",
                    "draft tokens accepted by the verify dispatch",
                    proposer=p, **lbl)
                for p in ("ngram", "truncated")},
            "radix_nodes": reg.gauge(
                "serving_radix_nodes",
                "radix prefix-cache tree nodes currently held", **lbl),
            "radix_hits": reg.counter(
                "serving_radix_hit_tokens_total",
                "prompt tokens matched in the radix prefix cache "
                "instead of prefilled", **lbl),
            "radix_evict": reg.counter(
                "serving_radix_evictions_total",
                "radix prefix-cache nodes evicted under pool pressure",
                **lbl),
            "ttft": reg.timer("serving_ttft_seconds",
                              "submit-to-first-token latency", **lbl),
            "tpot": reg.timer("serving_tpot_seconds",
                              "mean per-token decode latency per "
                              "finished request", **lbl),
            "step": reg.timer("serving_step_seconds",
                              "one continuous-batching decode dispatch",
                              **lbl),
            "goodput_frac": reg.gauge(
                GOODPUT_FRACTION_GAUGE,
                "useful token-positions / dispatched token-positions "
                "(the goodput ledger's rolling fraction)", **lbl),
            "goodput": {
                c: reg.counter(
                    fam, f"dispatched token-positions classified "
                         f"{c} by the goodput ledger", **lbl)
                for c, fam in GOODPUT_COUNTER_FAMILIES.items()
            },
            "ttft_queue": reg.timer(
                "serving_ttft_queue_wait_seconds",
                "TTFT decomposition: submit to admission wave", **lbl),
            "ttft_prefill": reg.timer(
                "serving_ttft_prefill_seconds",
                "TTFT decomposition: the admission dispatch", **lbl),
            "ttft_emit": reg.timer(
                "serving_ttft_first_emit_seconds",
                "TTFT decomposition: prefill completion to the consumer "
                "seeing the first token", **lbl),
        }
        # acceptance gauges start at 1.0, not the registry's default 0:
        # "no evidence yet" must read healthy, or the default alert
        # pack's acceptance-collapse rule (min over series < floor)
        # fires on every freshly-built server before its first
        # speculative dispatch
        fams["spec_accept"].set(1.0)
        for g in fams["spec_accept_by"].values():
            g.set(1.0)
        return fams

    def _slo_metrics(self):
        return self._resolve_metrics("_slo_cache", self._build_slo_metrics)

    def _build_slo_metrics(self, reg):
        lbl = {"model": self.name or "default"}
        return {
            "good": reg.counter("slo_requests_good_total",
                                "finished requests meeting the SLO",
                                **lbl),
            "bad": reg.counter("slo_requests_bad_total",
                               "requests missing the SLO (sheds "
                               "included)", **lbl),
            "burn": reg.gauge("slo_burn_rate",
                              "rolling-window error-budget burn rate "
                              "(1.0 = sustainable)", **lbl),
        }

    # ----------------------------------------------------------- shedding
    def _outstanding_tokens(self) -> int:
        """Outstanding decode work, from ACTUAL occupancy: live slots'
        remaining tokens plus, per queued request, the tokens it still
        owes (`n_left` — a requeued continuation owes only its tail)
        and a prefill cost proxy."""
        eng = self.engine
        out = int(eng.remaining[eng.active].sum())
        for req, _, _ in self._pending:
            # a continuation's effective prompt is prompt + emitted;
            # only the LENGTH matters here — don't materialize it
            out += req.n_left + blocks_needed(
                len(req.prompt) + req.emitted, eng.block_len)
        return out

    def _should_shed(self, req) -> Optional[str]:
        if self.max_queue is not None and len(self._pending) >= self.max_queue:
            return (f"admission queue full ({len(self._pending)} >= "
                    f"max_queue {self.max_queue})")
        if self.slo_ttft_s is not None and self._ewma_tok_s:
            projected = self._outstanding_tokens() / self._ewma_tok_s
            if projected > self.slo_ttft_s:
                return (f"projected queue delay {projected:.2f}s exceeds "
                        f"the {self.slo_ttft_s:.2f}s TTFT SLO at "
                        f"{self._ewma_tok_s:.1f} tok/s")
        return None

    # ---------------------------------------------------------- scheduler
    def _collect_loop(self):
        """The scheduler loop (replaces the coalescing collector):
        admissions, one decode dispatch, stream fan-out, eviction,
        gauges — then block on the queue only when fully idle."""
        eng = self.engine
        while self._running:
            try:
                progressed = self._schedule_once(eng)
            except Exception as e:  # noqa: BLE001 — a poisoned dispatch
                # must fail every waiting consumer, not hang them on a
                # dead scheduler (ParallelInference._execute's contract)
                self._fail_all(e)
                continue
            if not progressed:
                # fully idle: park on the queue (a submit wakes us)
                try:
                    item = self._queue.get(timeout=self.idle_wait_s)
                except queue.Empty:
                    continue
                self._queue_item_taken(item)
                if item is not None:
                    self._pending.append(item)

    def _fail_all(self, exc: BaseException):
        try:
            self.engine.drain_preempted()   # notices die with their reqs
        except Exception:  # noqa: BLE001 — engine state may be torn
            pass
        for slot, (req, fut, _) in list(self._slot2req.items()):
            try:
                self.engine.evict(slot)
            except Exception:  # noqa: BLE001 — engine state may be torn
                pass
            req.stream._fail(exc)
        self._slot2req.clear()
        for item in self._pending:
            # defensive: a foreign queue item without a stream must not
            # re-raise out of the failure path and kill the scheduler
            stream = getattr(item[0], "stream", None)
            if stream is not None:
                stream._fail(exc)
            elif len(item) > 1 and hasattr(item[1], "set_exception") \
                    and not item[1].done():
                item[1].set_exception(exc)
        self._pending.clear()

    def _schedule_once(self, eng) -> bool:
        m = self._serving_metrics()
        progressed = False
        # ------------------------------------------ control requests
        # (prefix registrations from foreign threads — the engine is
        # scheduler-thread-only by contract)
        if self._drain_control(eng):
            progressed = True
        # -------------------------------------------- cancellations
        for slot, (req, fut, _) in list(self._slot2req.items()):
            if req.stream.cancelled:
                eng.evict(slot)
                del self._slot2req[slot]
                if m is not None:
                    m["evicted"].inc()
                req.stream._finish()   # partial tokens, clean close
                progressed = True
        # cancelled while QUEUED: reap anywhere in line, not only at
        # the head — stranded entries otherwise keep counting toward
        # max_queue and the shed projection, shedding real requests
        # on phantom load
        if any(item[0].stream.cancelled for item in self._pending):
            kept = []
            for item in self._pending:
                if item[0].stream.cancelled:
                    item[0].stream._finish()
                    progressed = True
                else:
                    kept.append(item)
            self._pending = kept
        # ----------------------------------------------- admissions
        while True:
            try:
                item = self._queue.get_nowait()
            except queue.Empty:
                break
            self._queue_item_taken(item)
            if item is None:
                continue
            req = item[0]
            if req.stream.cancelled:
                req.stream._finish()
                continue
            reason = self._should_shed(req)
            if reason is not None:
                if m is not None:
                    m["shed"].inc()
                self._note_shed(req, reason)
                req.stream._fail(ShedError(reason))
                continue
            self._pending.append(item)
        while self._pending:
            head = self._pending[0]
            if head[0].stream.cancelled:
                self._pending.pop(0)
                head[0].stream._finish()
                continue
            # continuation length = prompt + emitted; only the LENGTH
            # matters for the capacity check — don't materialize it
            # UNLESS prefixes are registered (a head request riding a
            # shared prefix needs far fewer fresh blocks than its
            # length suggests; judging it by length alone could stall
            # the queue forever behind a perfectly admittable head)
            if not eng.can_admit(len(head[0].prompt) + head[0].emitted,
                                 head[0].n_left,
                                 prompt_ids=(head[0].effective_prompt()
                                             if (eng.has_prefixes
                                                 or eng._radix is not None)
                                             else None)):
                # a head that can NEVER be admitted must shed, not
                # wait — waiting would wedge the FIFO queue (and
                # everything behind it) forever. Under today's sharing
                # model this cannot fire (releasing a prefix returns
                # exactly the blocks a rider stops sharing, so a
                # request accepted via check_budget stays admissible);
                # the re-check is the INVARIANT'S enforcement point, so
                # a future sharing mode that breaks the arithmetic
                # degrades to a clean ShedError instead of a hang
                try:
                    eng.check_budget(
                        len(head[0].prompt) + head[0].emitted,
                        head[0].n_left,
                        prompt_ids=head[0].effective_prompt())
                except ValueError as e:
                    self._pending.pop(0)
                    if m is not None:
                        m["shed"].inc()
                    self._note_shed(head[0], str(e))
                    head[0].stream._fail(ShedError(str(e)))
                    progressed = True
                    continue
                break    # FIFO: never leapfrog the head request
            # admission WAVE: the FIFO prefix — prompt lengths may be
            # HETEROGENEOUS (the engine bucket-pads them into one
            # prefill dispatch) — goes through ONE batched prefill +
            # ONE fused pages/first-token dispatch (the engine stops
            # the wave itself at slot/block capacity)
            wave = []
            for item in self._pending:
                if item[0].stream.cancelled:
                    break
                wave.append(item)
                if len(wave) >= eng.free_slots:
                    break   # admission can never exceed free slots —
                    # don't build request dicts for a deep backlog
            t0p = time.perf_counter()
            admitted = eng.admit_many([
                dict(prompt_ids=it[0].effective_prompt(),
                     n_tokens=it[0].n_left, request_id=id(it[0]),
                     temperature=it[0].temperature,
                     top_p=it[0].top_p, rng=it[0].rng,
                     emit_start=it[0].emit_base + it[0].emitted)
                for it in wave])
            if not admitted:
                break
            if self.dispatch_floor_s is not None:
                dtp = time.perf_counter() - t0p
                if dtp < self.dispatch_floor_s:
                    # the prefill wave is device work too — under the
                    # emulated floor it must overlap across replicas
                    # the same way decode dispatches do
                    time.sleep(self.dispatch_floor_s - dtp)
            t1p = time.perf_counter()
            now = time.monotonic()
            for (slot, first, done), (req, fut, t_submit) in zip(
                    admitted, wave):
                self._pending.pop(0)
                fresh = req.stream.t_first is None
                req.stream._emit(first, now)
                tr = req.stream.trace
                if tr is not None:
                    # host-side stamps only — the wave's device work is
                    # already timed by t0p/t1p, no extra syncs
                    info = eng.admit_info.get(slot) or {}
                    if fresh:
                        tr.phase("queued", tr.t_created, t0p)
                    tr.phase("prefill", t0p, t1p,
                             wave_width=len(admitted), slot=slot,
                             continuation=not fresh, **info)
                    if info.get("cow_fork"):
                        tr.event("cow_fork", slot=slot)
                if m is not None:
                    m["tokens"].inc()
                    if fresh:
                        # a requeued continuation was already counted
                        # (and its TTFT observed) at first admission
                        m["requests"].inc()
                        m["ttft"].observe(now - t_submit)
                if done:
                    self._finish(req, m)
                else:
                    req.slot = slot
                    self._slot2req[slot] = (req, fut, t_submit)
            progressed = True
        # --------------------------------------------------- decode
        if eng.active.any():
            t0 = time.perf_counter()
            emitted, finished = eng.step(speculate=self._spec_policy(),
                                         proposers=self._spec_proposers())
            dt = time.perf_counter() - t0
            if self.dispatch_floor_s is not None \
                    and dt < self.dispatch_floor_s:
                time.sleep(self.dispatch_floor_s - dt)
                dt = self.dispatch_floor_s   # EWMA/trace see the
                # emulated device rate, not the host-compute rate
            # dispatch-level speculative deltas for trace attribution —
            # read BEFORE _spec_update advances the *_seen cursors
            d_spec_prop = eng.spec_proposed_total - self._spec_proposed_seen
            d_spec_acc = eng.spec_accepted_total - self._spec_accepted_seen
            self._spec_update(m)
            now = time.monotonic()
            # pool-pressure preemptions (incremental allocation):
            # requeue each evicted request as a continuation at the
            # HEAD of the admission queue — it predates everything
            # queued, and its emitted tokens stand (the engine
            # re-admits prompt+emitted at the same rng emit offset)
            preempted = eng.drain_preempted()
            if preempted:
                requeued = []
                for note in preempted:
                    entry = self._slot2req.pop(note["slot"], None)
                    if entry is not None:
                        requeued.append(entry)
                        tr = entry[0].stream.trace
                        if tr is not None:
                            tr.event("preempt_requeue",
                                     emitted=int(note.get("emitted", 0)))
                self._pending[:0] = requeued
                progressed = True
            n_tok = sum(len(ts) for ts in emitted.values())
            if m is not None and n_tok:
                m["step"].observe(dt)
                m["tokens"].inc(n_tok)
            if n_tok and dt > 0:
                rate = n_tok / dt
                self._ewma_tok_s = (rate if self._ewma_tok_s is None
                                    else 0.8 * self._ewma_tok_s
                                    + 0.2 * rate)
            t1 = t0 + dt
            for slot, toks in emitted.items():
                stream = self._slot2req[slot][0].stream
                stream._emit_many(toks, now)
                tr = stream.trace
                if tr is not None:
                    args = {"tokens": len(toks)}
                    if d_spec_prop:
                        args["spec_proposed"] = d_spec_prop
                        args["spec_accepted"] = d_spec_acc
                    tr.phase("decode", t0, t1, **args)
            for slot in finished:
                req, fut, _ = self._slot2req.pop(slot)
                self._finish(req, m)
            progressed = True
        # --------------------------------------------------- gauges
        if m is not None:
            m["queue"].set(self.queue_depth())
            m["slots"].set(eng.active_slots)
            m["blocks"].set(eng.free_blocks)
            m["pool_free"].set(eng.pool.free_blocks)
            m["pool_used"].set(eng.pool.used_blocks)
            if eng.block_grants_total > self._grants_seen:
                m["grants"].inc(eng.block_grants_total
                                - self._grants_seen)
                self._grants_seen = eng.block_grants_total
            if eng.evict_requeue_total > self._requeue_seen:
                m["requeue"].inc(eng.evict_requeue_total
                                 - self._requeue_seen)
                self._requeue_seen = eng.evict_requeue_total
            if eng.has_prefixes or eng.prefix_hits_total:
                m["prefix_shared"].set(eng.pool.allocator.shared_blocks)
                if eng.prefix_hits_total > self._prefix_hits_seen:
                    m["prefix_hits"].inc(eng.prefix_hits_total
                                         - self._prefix_hits_seen)
                    m["prefix_saved"].inc(eng.prefix_tokens_saved_total
                                          - self._prefix_saved_seen)
                    self._prefix_saved_seen = eng.prefix_tokens_saved_total
                    self._prefix_hits_seen = eng.prefix_hits_total
            if eng._radix is not None:
                m["radix_nodes"].set(eng._radix.nodes)
                if eng.radix_hit_tokens_total > self._radix_hits_seen:
                    m["radix_hits"].inc(eng.radix_hit_tokens_total
                                        - self._radix_hits_seen)
                    self._radix_hits_seen = eng.radix_hit_tokens_total
                if eng.radix_evictions_total > self._radix_evict_seen:
                    m["radix_evict"].inc(eng.radix_evictions_total
                                         - self._radix_evict_seen)
                    self._radix_evict_seen = eng.radix_evictions_total
            # goodput ledger mirror: per-class counter deltas + the
            # rolling fraction (host ints the dispatch sites already
            # wrote — zero extra syncs)
            gp = eng.goodput
            for cls, ctr in m["goodput"].items():
                total = gp.classes[cls]
                seen = self._goodput_seen.get(cls, 0)
                if total > seen:
                    ctr.inc(total - seen)
                    self._goodput_seen[cls] = total
            m["goodput_frac"].set(gp.goodput_fraction())
        return progressed

    # ------------------------------------------------ speculative policy
    def _spec_policy(self) -> Optional[bool]:
        """Whether the next dispatch drafts: None (engine default) when
        speculation is off or healthy; False while the accept-rate EWMA
        sits under `spec_accept_floor` — except for one probe dispatch
        every `spec_probe_every`, which re-measures the workload."""
        if not self.engine.spec_k:
            return None
        if not self._spec_disabled:
            return True
        self._spec_probe_in -= 1
        if self._spec_probe_in <= 0:
            self._spec_probe_in = self.spec_probe_every
            return True                      # probe dispatch
        return False

    def _spec_proposers(self) -> Optional[tuple]:
        """Per-proposer arbitration on top of `_spec_policy`'s global
        enable/disable: when the truncated-layer drafter is configured
        and the n-gram proposer's OWN acceptance EWMA has collapsed
        below the floor while the drafter's hasn't, restrict drafting
        to the truncated backend — its K-wide scan is only worth
        dispatching on lanes it can actually fill, and a dead n-gram
        cache (non-repetitive traffic) would otherwise keep winning
        the proposal race with garbage continuations. Returns None
        (engine default: all proposers) otherwise; if BOTH EWMAs sink,
        the global latch above disables speculation outright."""
        eng = self.engine
        if not eng.spec_k or eng._draft_plan is None:
            return None
        ng = self._spec_prop_ewma["ngram"]
        tr = self._spec_prop_ewma["truncated"]
        if ng is not None and ng < self.spec_accept_floor \
                and (tr is None or tr >= self.spec_accept_floor):
            return ("truncated",)
        return None

    def _spec_update(self, m):
        """Fold the engine's per-dispatch speculative counters into the
        acceptance EWMA and flip the auto-disable latch."""
        eng = self.engine
        if not eng.spec_k:
            return
        d_prop = eng.spec_proposed_total - self._spec_proposed_seen
        d_acc = eng.spec_accepted_total - self._spec_accepted_seen
        d_emit = eng.spec_emitted_total - self._spec_emitted_seen
        d_disp = eng.spec_dispatches_total - self._spec_dispatches_seen
        self._spec_proposed_seen = eng.spec_proposed_total
        self._spec_accepted_seen = eng.spec_accepted_total
        self._spec_emitted_seen = eng.spec_emitted_total
        self._spec_dispatches_seen = eng.spec_dispatches_total
        if d_disp < 1:
            return                           # chunked dispatch — no data
        # a dispatch where the proposer drafted NOTHING is also
        # evidence against speculation: it paid the K-wide score
        # program for one token per slot. Counting it as acceptance 0
        # lets the auto-disable engage on non-repetitive traffic the
        # suffix cache can't draft on — otherwise the EWMA never
        # updates and drafting runs at 1 token/dispatch forever
        rate = d_acc / d_prop if d_prop > 0 else 0.0
        self._spec_accept_ewma = (
            rate if self._spec_accept_ewma is None
            else 0.8 * self._spec_accept_ewma + 0.2 * rate)
        self._spec_tpd_ewma = (
            d_emit / d_disp if self._spec_tpd_ewma is None
            else 0.8 * self._spec_tpd_ewma + 0.2 * d_emit / d_disp)
        if not self._spec_disabled \
                and self._spec_accept_ewma < self.spec_accept_floor:
            self._spec_disabled = True
            self._spec_probe_in = self.spec_probe_every
        elif self._spec_disabled \
                and self._spec_accept_ewma >= self.spec_accept_floor:
            self._spec_disabled = False
        # per-proposer EWMAs (arbitration inputs for _spec_proposers):
        # same α, same "no data this dispatch → no update" rule — a
        # proposer that drafted nothing is judged only when it ran
        for prop in ("ngram", "truncated"):
            pp, pa = self._spec_prop_seen[prop]
            tot_p = eng.spec_proposed_by[prop]
            tot_a = eng.spec_accepted_by[prop]
            d_pp, d_pa = tot_p - pp, tot_a - pa
            self._spec_prop_seen[prop] = (tot_p, tot_a)
            if d_pp > 0:
                r = d_pa / d_pp
                prev = self._spec_prop_ewma[prop]
                self._spec_prop_ewma[prop] = (
                    r if prev is None else 0.8 * prev + 0.2 * r)
            if m is not None and d_pp > 0:
                m["spec_proposed_by"][prop].inc(d_pp)
                if d_pa > 0:
                    m["spec_accepted_by"][prop].inc(d_pa)
                m["spec_accept_by"][prop].set(self._spec_prop_ewma[prop])
        if m is not None:
            m["spec_accept"].set(self._spec_accept_ewma)
            if self._spec_tpd_ewma is not None:
                m["spec_tpd"].set(self._spec_tpd_ewma)

    def _note_shed(self, req, reason: str):
        """Shed bookkeeping beyond the counter: trace annotation (the
        router's/scheduler's decision becomes auditable per request),
        SLO budget spend, and a rate-limited flight-recorder event."""
        tr = req.stream.trace
        if tr is not None:
            tr.event("shed", reason=reason)
        slo = self._slo_tracker
        if slo is not None:
            slo.record_shed()
            sm = self._slo_metrics()
            if sm is not None:
                sm["bad"].inc()
                sm["burn"].set(slo.burn_rate())
        # shed BURSTS are a control-plane signal; single events at
        # request rate would flood the ring, so coalesce to ≤1/s
        self._shed_recent += 1
        now = time.monotonic()
        if now - self._shed_last_emit >= 1.0:
            GLOBAL_FLIGHT_RECORDER.record(
                "shed_burst", server=self.name,
                count=self._shed_recent, reason=reason)
            self._shed_recent = 0
            self._shed_last_emit = now

    def _finish(self, req, m):
        st = req.stream
        n = len(st.tokens)
        ttft = (st.t_first - st.t_submit) if st.t_first is not None \
            else None
        tpot = ((st.t_last - st.t_first) / (n - 1)
                if st.t_first is not None and n > 1 else None)
        tr = st.trace
        if tr is not None:
            if self._draining:
                tr.event("drain_at_swap")
            tr.annotate(ttft_s=ttft, tpot_s=tpot)
        slo = self._slo_tracker
        if slo is not None:
            good = slo.record(ttft=ttft, tpot=tpot)
            sm = self._slo_metrics()
            if sm is not None:
                sm["good" if good else "bad"].inc()
                sm["burn"].set(slo.burn_rate())
            if tr is not None:
                tr.annotate(slo_good=good)
        st._finish()
        if m is not None and st.t_first is not None and n > 1:
            m["tpot"].observe((st.t_last - st.t_first) / (n - 1))
        if m is not None and tr is not None:
            # TTFT decomposition from the stamps the trace already
            # carries (queued/prefill phases + the ttft annotation)
            dec = ttft_decomposition(tr)
            if dec is not None:
                m["ttft_queue"].observe(dec["queue_wait_s"])
                m["ttft_prefill"].observe(dec["prefill_s"])
                m["ttft_emit"].observe(dec["first_emit_s"])

    # ---------------------------------------------------------- lifecycle
    def start(self):
        # a restarted scheduler would run over an engine whose slots
        # were force-retired by stop() and whose streams were failed —
        # refuse loudly instead of corrupting the allocator
        if self._stopped:
            raise ServerStoppedError(
                "GenerationServer was stopped; start() cannot revive it "
                "— build a fresh server (the engine's slot/allocator "
                "state was retired at stop())")
        return super().start()

    def drain(self, timeout: Optional[float] = None) -> bool:
        """Zero-downtime handoff seam: close admissions (new
        `generate_async` raises `ServerDrainingError`) and block until
        every already-submitted stream — queued AND in-flight — has
        finished. Returns True when fully drained, False on timeout
        (admissions stay closed either way).

        The barrier is the open-stream count (TokenStream close hooks),
        not scheduler-state inspection: a request between the queue and
        the pending list is invisible to both, and declaring drained
        while it's in limbo would drop a stream at the subsequent
        stop(). The engine is never touched from here — the warmup
        counter-reset and incremental-allocation invariants
        (docs/SERVING.md) belong to the scheduler thread alone."""
        with self._open_lock:
            # flag-set and count-read share the submit path's lock:
            # see the generate_async re-check
            self._draining = True
        # goodput: dispatch work from here on belongs to the swap
        # window — delivered, but attributed to drain (the fraction
        # visibly dips during a swap, which is the operator's signal).
        # The flag flip is racy against an in-flight dispatch by one
        # dispatch at most; the ledger's mode reroute keeps every
        # counter monotone either way.
        self.engine.goodput.set_mode("drain")
        deadline = (None if timeout is None
                    else time.monotonic() + float(timeout))
        while self.open_streams > 0:
            if not self._running:
                # scheduler gone (stop() raced us): whatever is left
                # has been failed — drained in the "nothing in flight"
                # sense, but not cleanly
                return self.open_streams == 0
            if deadline is not None and time.monotonic() > deadline:
                return False
            time.sleep(0.002)
        return True

    def stop(self):
        # idempotent: a second stop() (or stop() after shutdown()) is a
        # no-op — the first one already failed every stream and joined
        # the scheduler; re-running the teardown over cleared state
        # must not raise or double-fail anything
        if self._stopped:
            return
        self._stopped = True
        # inherited stop() joins with a 5 s cap and proceeds — here a
        # single decode chunk can legitimately run longer (large model
        # x steps_per_dispatch), and mutating engine/slot state while
        # _schedule_once is still inside eng.step() corrupts the
        # allocator and fails streams with spurious errors. Wait the
        # scheduler out; only touch the engine once its thread is dead.
        self._running = False
        scheduler_dead = True
        if self._collector is not None:
            self._queue.put(None)   # wake an idle park
            self._collector.join(timeout=600)
            scheduler_dead = not self._collector.is_alive()
            self._collector = None
        self._fail_pending()        # drains + fails anything queued
        # in-flight sequences: evict and fail their streams so no
        # consumer hangs on an iterator that will never close
        for slot, (req, fut, _) in list(self._slot2req.items()):
            if scheduler_dead:
                try:
                    self.engine.evict(slot)
                except ValueError:
                    pass
            req.stream._fail(RuntimeError(
                "GenerationServer stopped before this request finished"))
        self._slot2req.clear()
        for req, fut, _ in self._pending:
            req.stream._fail(RuntimeError(
                "GenerationServer stopped before this request was "
                "admitted"))
        self._pending.clear()
        # control requests (prefix registrations) still queued: fail
        # their futures so no caller blocks on a dead scheduler
        self._fail_control()

    def _fail_control(self):
        while True:
            try:
                _, _, fut = self._control.get_nowait()
            except queue.Empty:
                break
            if not fut.done():
                fut.set_exception(RuntimeError(
                    "GenerationServer stopped before this control "
                    "request was applied"))

    def _fail_pending(self):
        """Queue items here are (request, future, t) — fail the STREAM
        (which resolves the future and closes the iterator), not just
        the future."""
        while True:
            try:
                item = self._queue.get_nowait()
            except queue.Empty:
                break
            self._queue_item_taken(item)
            if item is None:
                continue
            req = item[0]
            if hasattr(req, "stream"):
                req.stream._fail(RuntimeError(
                    "GenerationServer stopped before this request was "
                    "executed"))
            elif not item[1].done():
                item[1].set_exception(RuntimeError(
                    "GenerationServer stopped before this request was "
                    "executed"))
