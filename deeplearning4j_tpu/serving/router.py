"""FleetRouter — the fleet's front-end request plane.

One router fronts N named models (generation backends hosted by a
`FleetServer`, plus plain `.output()` models for the forward-serving
routes): it resolves the ACTIVE server per request — which is what
makes hot-swap invisible to clients, a submit that races the swap
pointer-flip retries against the freshly-resolved successor — applies
the admission policy, and tags every stream with the (model, version)
it was served by.

Admission policy (weighted SLO shedding across models): each model's
projected queue delay is the serving tier's existing EWMA estimator —
outstanding decode work / measured token throughput
(`GenerationServer._should_shed`'s math) — but the router compares it
against ``slo_ttft_s * weight(model)``: a weight-2 model tolerates
twice the delay a weight-1 model does, so under fleet-wide pressure
the low-priority models shed FIRST while the high-priority ones keep
admitting. `max_queue` is the per-model hard backstop before any
throughput estimate exists. Shed requests raise `ShedError` (locally)
or carry it across the wire (`wire.reply_error`).

Fair-share admission (multi-tenant floors): when the router fronts a
`TenantFleet`, model names are TENANTS of one shared base and the
device is a shared resource — a heavy tenant's flood degrades every
tenant's throughput EWMA, so the light tenant's projected delay grows
through no fault of its own and plain SLO shedding starves it.
`set_share_floor(tenant, floor)` grants a tenant a guaranteed
fraction of recently-admitted fleet work (windowed token accounting,
`share_window_s`): while its admitted share sits below the floor, the
projected-delay shed is bypassed (only its own `max_queue` backstop
applies), and any tenant consuming MORE than its weight-proportional
fair share has its budget tightened while a floored tenant is being
squeezed — the heavy tenant absorbs the shedding. Per-tenant
`tenant=`-labeled families (`fleet_tenant_shed_total`,
`fleet_tenant_admitted_tokens_total`, `fleet_tenant_share`,
`fleet_tenant_floor_admits_total`) make the division auditable.

Transport plane: `serve()` starts a pump thread consuming
`<prefix>.requests` frames from a `streaming.Transport` and a relay
thread fanning each stream's token chunks onto
`<prefix>.replies.<request_id>` — clients (`FleetClient`) hold only a
transport, never a server reference. The relay forwards per-CHUNK (the
scheduler already batches emissions per dispatch), so the transport
sees one message per decode chunk, not per token.
"""

from __future__ import annotations

import logging
import os
import queue
import threading
import time
import uuid
from collections import deque
from concurrent.futures import Future
from typing import Dict, List, Optional, Tuple

import numpy as np

from deeplearning4j_tpu import monitor
from deeplearning4j_tpu.monitor.flightrec import GLOBAL_FLIGHT_RECORDER
from deeplearning4j_tpu.monitor.reqtrace import RequestTrace
from deeplearning4j_tpu.serving import wire
from deeplearning4j_tpu.serving.replica import ReplicaLostError
from deeplearning4j_tpu.serving.server import (
    ServerDrainingError,
    ShedError,
    TokenStream,
)

log = logging.getLogger("deeplearning4j_tpu.serving.router")


class UnknownModelError(RuntimeError):
    """Request named a model the router doesn't front."""


class FleetRouter:
    def __init__(self, fleet=None, *, slo_ttft_s: Optional[float] = None,
                 max_queue: Optional[int] = None,
                 weights: Optional[Dict[str, float]] = None,
                 transport=None, prefix: str = "fleet",
                 poll_s: float = 0.005,
                 replica_pending_ttl_s: float = 0.75,
                 share_floors: Optional[Dict[str, float]] = None,
                 share_window_s: float = 10.0):
        self.fleet = fleet
        self.slo_ttft_s = slo_ttft_s
        self.max_queue = max_queue
        self.weights = dict(weights or {})
        # fair-share admission state: a sliding window of per-tenant
        # offered/admitted token counts. `share_floors` maps tenant ->
        # guaranteed fraction of admitted fleet work; the log is one
        # deque of (t, name, n_tokens, admitted) with running totals so
        # admitted_share() is O(expired entries), not O(window)
        self.share_floors: Dict[str, float] = {}
        self.share_window_s = float(share_window_s)
        self._share_lock = threading.Lock()
        self._share_log: deque = deque()
        self._share_admitted: Dict[str, int] = {}
        self._share_offered: Dict[str, int] = {}
        self._share_admitted_total = 0
        for k, v in (share_floors or {}).items():
            self.set_share_floor(k, v)
        self.transport = transport
        self.prefix = prefix
        self.poll_s = float(poll_s)
        self._outputs: Dict[str, object] = {}
        self._out_inflight: Dict[str, int] = {}
        self._out_lock = threading.Lock()
        # horizontal serving: one ReplicaSet per replicated model, plus
        # this router's own not-yet-absorbed token debt per replica —
        # the directory's load gauges refresh once per heartbeat, so a
        # burst submitted between refreshes must see its OWN submissions
        # or every request in the burst lands on the same "least-loaded"
        # replica. Each debt entry is [n_tokens, t_submit] and counts
        # only until the replica's next heartbeat has had time to land
        # (replica_pending_ttl_s): after that the advertised
        # outstanding_tokens gauge includes the same request, and
        # counting both halves would double-count nearly every
        # in-flight request for its whole lifetime
        self._replica_sets: Dict[str, object] = {}
        self._replica_migrations: Dict[str, int] = {}
        self._replica_pending: Dict[str, List[list]] = {}
        self._replica_lock = threading.Lock()
        self.replica_pending_ttl_s = float(replica_pending_ttl_s)
        self._metrics_cache = None
        # transport-plane threads + active remote streams
        self._running = False
        self._pump: Optional[threading.Thread] = None
        self._relay: Optional[threading.Thread] = None
        self._active: Dict[str, dict] = {}
        self._active_lock = threading.Lock()
        # shed-burst flight-recorder rate limit (≤1 event/s)
        self._shed_recent = 0
        self._shed_last_emit = 0.0

    # ------------------------------------------------------------ metrics
    def _metrics(self):
        from deeplearning4j_tpu import monitor
        return monitor.resolve_cached_metrics(
            self, "_metrics_cache", lambda reg: {
                "streams": lambda name: reg.counter(
                    "fleet_streams_total",
                    "generation streams routed per model", model=name),
                "shed": lambda name: reg.counter(
                    "fleet_shed_total",
                    "requests shed by the router admission policy",
                    model=name),
                "lost": lambda name: reg.counter(
                    "fleet_replica_lost_total",
                    "requests failed because no live replica could "
                    "take them", model=name),
                "outputs": lambda name: reg.counter(
                    "fleet_output_requests_total",
                    "one-shot output() requests routed per model",
                    model=name),
                "t_shed": lambda name: reg.counter(
                    "fleet_tenant_shed_total",
                    "requests shed per tenant by the fair-share "
                    "admission policy", tenant=name),
                "t_admitted": lambda name: reg.counter(
                    "fleet_tenant_admitted_tokens_total",
                    "generation tokens admitted per tenant",
                    tenant=name),
                "t_share": lambda name: reg.gauge(
                    "fleet_tenant_share",
                    "tenant's fraction of admitted fleet work over "
                    "the share window", tenant=name),
                "t_floor": lambda name: reg.counter(
                    "fleet_tenant_floor_admits_total",
                    "admissions granted under fair-share floor "
                    "protection (projected-delay shed bypassed)",
                    tenant=name),
            })

    def set_weight(self, name: str, weight: float):
        """Shedding priority: model `name` tolerates
        `slo_ttft_s * weight` of projected delay before shedding
        (weight > 1 sheds later than the fleet default, < 1 earlier)."""
        if weight <= 0:
            raise ValueError(f"weight must be > 0; got {weight}")
        self.weights[name] = float(weight)

    # --------------------------------------------------------- fair share
    def set_share_floor(self, name: str, floor: float):
        """Guarantee tenant `name` at least `floor` (a fraction in
        [0, 1)) of the recently-admitted fleet work: while its admitted
        share sits below the floor, the projected-delay shed is
        bypassed for it (the per-tenant `max_queue` hard backstop still
        applies). The sum of all floors must stay below 1 — the fleet
        cannot guarantee more than itself."""
        floor = float(floor)
        if not 0.0 <= floor < 1.0:
            raise ValueError(f"share floor must be in [0, 1); "
                             f"got {floor}")
        others = sum(v for k, v in self.share_floors.items()
                     if k != name)
        if others + floor >= 1.0:
            raise ValueError(
                f"share floors must sum below 1.0; {name!r} at "
                f"{floor} would bring the total to {others + floor}")
        self.share_floors[name] = floor

    def _note_share(self, name: str, n_tokens: int, admitted: bool):
        """Record one routing decision in the sliding share window and
        refresh the tenant's share gauge."""
        now = time.monotonic()
        n = int(n_tokens)
        with self._share_lock:
            self._share_log.append((now, name, n, admitted))
            self._share_offered[name] = \
                self._share_offered.get(name, 0) + n
            if admitted:
                self._share_admitted[name] = \
                    self._share_admitted.get(name, 0) + n
                self._share_admitted_total += n
            self._trim_share(now)
        m = self._metrics()
        if m is not None:
            if admitted:
                m["t_admitted"](name).inc(n)
            m["t_share"](name).set(self.admitted_share(name))

    def _trim_share(self, now: float):
        """Drop window-expired entries (caller holds _share_lock)."""
        cutoff = now - self.share_window_s
        log_ = self._share_log
        while log_ and log_[0][0] < cutoff:
            _, nm, n, adm = log_.popleft()
            left = self._share_offered.get(nm, 0) - n
            if left > 0:
                self._share_offered[nm] = left
            else:
                self._share_offered.pop(nm, None)
            if adm:
                left = self._share_admitted.get(nm, 0) - n
                if left > 0:
                    self._share_admitted[nm] = left
                else:
                    self._share_admitted.pop(nm, None)
                self._share_admitted_total = max(
                    0, self._share_admitted_total - n)

    def admitted_share(self, name: str) -> float:
        """Tenant's fraction of admitted fleet tokens over the share
        window (0.0 when the window is empty)."""
        with self._share_lock:
            self._trim_share(time.monotonic())
            if self._share_admitted_total <= 0:
                return 0.0
            return (self._share_admitted.get(name, 0)
                    / self._share_admitted_total)

    def _floor_protected(self, name: str) -> bool:
        """True while `name` sits below its configured share floor —
        its projected-delay shed is bypassed (it is being squeezed by
        OTHER tenants' load on the shared device, not by itself)."""
        floor = self.share_floors.get(name)
        if floor is None:
            return False
        return self.admitted_share(name) < floor

    def _overshare_scale(self, name: str) -> float:
        """SLO-budget multiplier in (0, 1] for a tenant consuming more
        than its weight-proportional fair share WHILE some floored
        tenant with live demand is starved below its floor: the heavy
        tenant's delay budget tightens by fair/actual (floored at 1/4)
        so it sheds first and the floor-protected admissions have
        capacity to land on."""
        if not self.share_floors or self.fleet is None:
            return 1.0
        try:
            names = self.fleet.names()
        except Exception:  # noqa: BLE001 — fleet mid-teardown
            return 1.0
        if name not in names or len(names) < 2:
            return 1.0
        share = self.admitted_share(name)
        wsum = sum(self.weights.get(n, 1.0) for n in names)
        fair = (self.weights.get(name, 1.0) / wsum) if wsum > 0 else 1.0
        if share <= fair:
            return 1.0
        with self._share_lock:
            self._trim_share(time.monotonic())
            offered = dict(self._share_offered)
        starving = any(
            n != name and offered.get(n, 0) > 0
            and self.admitted_share(n) < f
            for n, f in self.share_floors.items())
        if not starving:
            return 1.0
        return max(0.25, fair / share)

    # ----------------------------------------------------------- resolve
    def _resolve(self, name: str):
        """(server, version) of the ACTIVE backend for `name` — one
        atomic read of the fleet's swap pointer."""
        if self.fleet is None or not self.fleet.has(name):
            known = ([] if self.fleet is None
                     else self.fleet.names()) + sorted(self._outputs)
            raise UnknownModelError(
                f"router fronts no generation model {name!r} "
                f"(known: {known})")
        return self.fleet.active(name)

    # ---------------------------------------------------------- shedding
    @staticmethod
    def _outstanding_tokens(server) -> int:
        """The server's own outstanding-work estimate PLUS the tokens
        owed by requests still sitting in its submit queue: the server
        computes its projection on the scheduler thread after moving
        queue items to the pending list, but the router projects from
        OUTSIDE — at submit time a just-enqueued request lives in
        `_queue`, which `server._outstanding_tokens()` cannot see.
        `queued_tokens` is the server's O(1) running counter — copying
        a 10k-deep queue under its mutex per submit would make the
        projection itself the bottleneck."""
        return server._outstanding_tokens() + server.queued_tokens

    def _should_shed(self, name: str, server) -> Optional[str]:
        depth = server.queue_depth()
        if self.max_queue is not None and depth >= self.max_queue:
            return (f"model {name!r} admission queue full "
                    f"({depth} >= max_queue {self.max_queue})")
        if self.slo_ttft_s is not None and server._ewma_tok_s:
            # the serving tier's own projected-delay estimator, scaled
            # by the model's weight — fleet-wide pressure sheds the
            # low-weight models first. A tenant past its fair share
            # while a floored tenant starves gets a TIGHTENED budget;
            # a tenant below its floor bypasses the delay shed.
            budget = (self.slo_ttft_s * self.weights.get(name, 1.0)
                      * self._overshare_scale(name))
            projected = (self._outstanding_tokens(server)
                         / server._ewma_tok_s)
            if projected > budget:
                if self._floor_protected(name):
                    m = self._metrics()
                    if m is not None:
                        m["t_floor"](name).inc()
                    return None
                return (f"model {name!r} projected delay "
                        f"{projected:.2f}s exceeds its weighted "
                        f"{budget:.2f}s TTFT budget at "
                        f"{server._ewma_tok_s:.1f} tok/s")
        return None

    # ------------------------------------------------------------ submit
    def submit(self, name: str, prompt_ids, n_tokens: int, *,
               temperature: float = 0.0, top_p: Optional[float] = None,
               rng=None,
               trace: Optional[RequestTrace] = None) -> TokenStream:
        """Route one generation request to `name`'s active server;
        returns its TokenStream tagged with ``.model``/``.version``.
        A submit racing a hot-swap's pointer flip sees the incumbent's
        `ServerDrainingError` and retries against the successor — the
        zero-dropped-streams contract covers the flip window.

        `trace` is upstream trace context (a pump-rehydrated remote
        trace); without one, the router mints the request's trace here
        — the earliest point that sees the routing decision, so a shed
        is annotated into the trace it rejected."""
        m = self._metrics()
        if trace is None and monitor.is_enabled():
            trace = RequestTrace(model=name)
        rset = self._replica_sets.get(name)
        if rset is not None:
            return self._submit_replicated(
                name, rset, prompt_ids, n_tokens, temperature=temperature,
                top_p=top_p, rng=rng, trace=trace)
        for _ in range(64):
            server, version = self._resolve(name)
            reason = self._should_shed(name, server)
            if reason is not None:
                if m is not None:
                    m["shed"](name).inc()
                    m["t_shed"](name).inc()
                if trace is not None:
                    # the router's shed decision, auditable per request
                    trace.event("shed", reason=reason, router=True)
                    trace.finish(status="shed")
                self._note_share(name, n_tokens, admitted=False)
                self._note_shed_burst(name, reason)
                raise ShedError(reason)
            try:
                stream = server.generate_async(
                    prompt_ids, n_tokens, temperature=temperature,
                    top_p=top_p, rng=rng, trace=trace)
            except ServerDrainingError:
                # swap in progress: the pointer flip happens before the
                # incumbent drains, so the next resolve sees the warmed
                # successor
                if trace is not None:
                    trace.event("drain_retry", model=name,
                                version=version)
                time.sleep(0.002)
                continue
            stream.model = name
            stream.version = version
            if trace is not None:
                trace.annotate(version=version)
            if m is not None:
                m["streams"](name).inc()
            self._note_share(name, n_tokens, admitted=True)
            return stream
        raise RuntimeError(
            f"model {name!r} stayed in draining state across retries — "
            f"is a swap stuck without a successor?")

    # ----------------------------------------------- horizontal replicas
    def attach_replicas(self, name: str, replica_set, *,
                        max_migrations: int = 3):
        """Front `name` with a horizontally-replicated backend: a
        `ReplicaSet` polling the elastic coordinator's serving
        directory. Submits to `name` now BALANCE before they shed —
        backends are ordered least-loaded first on their advertised
        gauges (projected delay = outstanding tokens / tok/s EWMA, plus
        this router's own unresolved submissions) and a request is
        refused only when EVERY live replica fails its admission check
        (queue full, or projected past the weighted SLO budget). A
        replica dying mid-stream migrates the request: nothing-received
        resubmits verbatim to any survivor, a partial stream continues
        as prompt+received with emit_start on a same-version replica —
        up to `max_migrations` hops before the typed `ReplicaLostError`
        surfaces to the caller."""
        self._replica_sets[name] = replica_set
        self._replica_migrations[name] = int(max_migrations)

    def detach_replicas(self, name: str):
        """Stop fronting `name` with replicas (the set itself is the
        caller's to close); subsequent submits fall back to the local
        fleet path."""
        self._replica_sets.pop(name, None)
        self._replica_migrations.pop(name, None)

    def replica_pending(self, token: str) -> int:
        """Tokens this router has submitted to `token` that its
        advertised gauges cannot see yet — the between-heartbeats half
        of the balance signal. An entry stops counting when its stream
        resolves OR when it outlives `replica_pending_ttl_s`: by then
        the replica's own heartbeat-refreshed `outstanding_tokens`
        covers the request, and the debt here must drop out or the
        projection counts those tokens twice."""
        now = time.monotonic()
        cutoff = now - self.replica_pending_ttl_s
        with self._replica_lock:
            entries = self._replica_pending.get(token)
            if not entries:
                return 0
            live = [e for e in entries if e[1] > cutoff]
            if len(live) != len(entries):
                if live:
                    self._replica_pending[token] = live
                else:
                    self._replica_pending.pop(token, None)
            return sum(e[0] for e in live)

    def _replica_order_key(self, backend):
        """Least-loaded ordering on the WORK gauges — outstanding
        tokens (advertised + this router's own unresolved submits),
        then queue depth — ties broken by token for stability.

        Deliberately NOT the projected-delay estimator: that divides
        by the throughput EWMA, and a freshly-warmed replica's EWMA
        comes from a 1-slot warmup dispatch — an order of magnitude
        below its full-batch rate — so delay-ordering starves exactly
        the replica that fan-out just added. Outstanding work is
        rate-free: a cold replica reads 0 and attracts traffic, which
        warms it. Projected delay stays where the SLO lives — the
        shed decision (`_replica_shed_reason`)."""
        tok, _client, meta = backend
        load = meta.get("load") or {}
        out = (int(load.get("outstanding_tokens") or 0)
               + self.replica_pending(tok))
        return (out, int(load.get("queue_depth") or 0), tok)

    def _replica_shed_reason(self, name: str, tok: str,
                             meta: dict) -> Optional[str]:
        """Per-replica admission check — `_should_shed` over advertised
        gauges instead of a live server reference."""
        load = meta.get("load") or {}
        depth = int(load.get("queue_depth") or 0)
        if self.max_queue is not None and depth >= self.max_queue:
            return (f"replica {tok} of {name!r} admission queue full "
                    f"({depth} >= max_queue {self.max_queue})")
        rate = float(load.get("ewma_tok_s") or 0.0)
        if self.slo_ttft_s is not None and rate > 0:
            out = (int(load.get("outstanding_tokens") or 0)
                   + self.replica_pending(tok))
            budget = (self.slo_ttft_s * self.weights.get(name, 1.0)
                      * self._overshare_scale(name))
            projected = out / rate
            if projected > budget:
                if self._floor_protected(name):
                    m = self._metrics()
                    if m is not None:
                        m["t_floor"](name).inc()
                    return None
                return (f"replica {tok} of {name!r} projected delay "
                        f"{projected:.2f}s exceeds its weighted "
                        f"{budget:.2f}s TTFT budget at {rate:.1f} tok/s")
        return None

    def _note_replica_submit(self, tok: str, n_tokens: int, stream):
        entry = [int(n_tokens), time.monotonic()]
        with self._replica_lock:
            self._replica_pending.setdefault(tok, []).append(entry)

        def _resolved(_f, tok=tok, entry=entry):
            with self._replica_lock:
                entries = self._replica_pending.get(tok)
                if entries is None:
                    return
                try:
                    entries.remove(entry)
                except ValueError:
                    pass                 # already expired out of view
                if not entries:
                    self._replica_pending.pop(tok, None)

        stream._fut.add_done_callback(_resolved)

    def _submit_replicated(self, name: str, rset, prompt_ids,
                           n_tokens: int, *, temperature: float,
                           top_p, rng, trace) -> "MigratingStream":
        m = self._metrics()
        prompt = np.asarray(prompt_ids)
        if prompt.ndim == 2 and prompt.shape[0] == 1:
            prompt = prompt[0]
        # mint the sampling rng HERE, not replica-side: a migrated
        # continuation must fold the SAME key at the same positions on
        # the survivor, so the key has to live with the logical stream
        if temperature and rng is None:
            rng = np.frombuffer(os.urandom(8), np.uint32).copy()
        ms = MigratingStream(
            self, name, rset, prompt, n_tokens, temperature=temperature,
            top_p=top_p, rng=rng, trace=trace,
            max_migrations=self._replica_migrations.get(name, 3))
        try:
            self._dispatch_replica(ms)
        except ShedError as e:
            if m is not None:
                m["shed"](name).inc()
                m["t_shed"](name).inc()
            if trace is not None:
                trace.event("shed", reason=str(e), router=True)
                trace.finish(status="shed")
            self._note_share(name, n_tokens, admitted=False)
            self._note_shed_burst(name, str(e))
            raise
        except ReplicaLostError as e:
            # no live replica could take it — finish the trace and
            # count it, or the failure leaks an unfinished RequestTrace
            # and stays invisible to error telemetry
            if m is not None:
                m["lost"](name).inc()
            if trace is not None:
                trace.event("replica_lost", reason=str(e), router=True)
                trace.finish(status="error",
                             error=type(e).__name__)
            raise
        if m is not None:
            m["streams"](name).inc()
        if trace is not None:
            trace.annotate(replica=ms.replica)
        self._note_share(name, n_tokens, admitted=True)
        return ms

    def _dispatch_replica(self, ms: "MigratingStream") -> None:
        """(Re)submit one logical stream to the best live replica.
        Balance-THEN-shed: candidates are tried least-loaded first and
        `ShedError` is raised only when every live one fails its
        admission check — a single overloaded replica never sheds a
        request another could serve. Called for the initial submit and
        again per migration hop (from the dead client's reader thread,
        via the attempt's done callback)."""
        ms._rset.refresh()
        name = ms.model
        committed = list(ms._committed)
        remaining = ms.n_tokens - len(committed)
        prompt = ms._prompt
        if committed:
            prompt = np.concatenate(
                [prompt, np.asarray(committed, prompt.dtype)])
        dead = set(ms._dead)
        cands = []
        for tok, client, meta in ms._rset.backends():
            if tok in dead or client.closed:
                continue
            if committed and ms._version_pin is not None \
                    and meta.get("version") is not None \
                    and int(meta["version"]) != ms._version_pin:
                # continuations must stay on their version: a partial
                # stream joined across versions would splice two
                # different models' numerics into one "stream"
                continue
            cands.append((tok, client, meta))
        if not cands:
            raise ReplicaLostError(
                f"no live replica of {name!r} can take this stream "
                f"(directory generation {ms._rset.generation}, "
                f"{len(dead)} known dead, version pin "
                f"{ms._version_pin})",
                request_id=ms.request_id, tokens=committed)
        reasons: List[str] = []
        for tok, client, meta in sorted(cands,
                                        key=self._replica_order_key):
            reason = self._replica_shed_reason(name, tok, meta)
            if reason is not None:
                reasons.append(reason)
                continue
            try:
                stream = client.submit(
                    name, prompt, remaining,
                    temperature=ms._temperature, top_p=ms._top_p,
                    rng=ms._rng, emit_start=len(committed),
                    trace_id=(None if ms.trace is None
                              else ms.trace.trace_id))
            except ReplicaLostError:
                # died between refresh and submit: same as dead in the
                # directory — move on to the next candidate
                ms._dead.append(tok)
                continue
            self._note_replica_submit(tok, remaining, stream)
            ms._bind(stream)
            return
        if reasons:
            raise ShedError(
                f"all {len(reasons)} live replicas of {name!r} are past "
                f"their admission budget — {reasons[0]}")
        raise ReplicaLostError(
            f"every live replica of {name!r} died at submit",
            request_id=ms.request_id, tokens=committed)

    def _note_shed_burst(self, name: str, reason: str):
        self._shed_recent += 1
        now = time.monotonic()
        if now - self._shed_last_emit >= 1.0:
            GLOBAL_FLIGHT_RECORDER.record(
                "shed_burst", source="router", model=name,
                count=self._shed_recent, reason=reason)
            self._shed_recent = 0
            self._shed_last_emit = now

    # ------------------------------------------------------- output plane
    def attach_output(self, name: str, model):
        """Front a plain forward model (anything with `.output(x)`) —
        the `ServingRoute` backend kind. Shares the router's naming,
        counters and max_queue backstop with the generation plane."""
        self._outputs[name] = model
        self._out_inflight.setdefault(name, 0)

    def route_output(self, name: str, x) -> np.ndarray:
        model = self._outputs.get(name)
        if model is None:
            raise UnknownModelError(
                f"router fronts no output model {name!r} "
                f"(known: {sorted(self._outputs)})")
        m = self._metrics()
        with self._out_lock:
            if (self.max_queue is not None
                    and self._out_inflight[name] >= self.max_queue):
                if m is not None:
                    m["shed"](name).inc()
                raise ShedError(
                    f"output model {name!r} has "
                    f"{self._out_inflight[name]} requests in flight "
                    f"(max_queue {self.max_queue})")
            self._out_inflight[name] += 1
        try:
            if m is not None:
                m["outputs"](name).inc()
            return np.asarray(model.output(x))
        finally:
            with self._out_lock:
                self._out_inflight[name] -= 1

    # ------------------------------------------------------ transport plane
    def serve(self) -> "FleetRouter":
        """Start consuming `<prefix>.requests` from the transport and
        relaying token chunks to each request's reply topic."""
        if self.transport is None:
            raise ValueError("router has no transport — pass transport= "
                             "to serve the request plane")
        if self._running:
            return self
        self._running = True
        self._pump = threading.Thread(target=self._pump_loop, daemon=True)
        self._relay = threading.Thread(target=self._relay_loop, daemon=True)
        self._pump.start()
        self._relay.start()
        return self

    def stop(self):
        self._running = False
        for t in (self._pump, self._relay):
            if t is not None:
                t.join(timeout=10)
        self._pump = self._relay = None
        # fail whatever was mid-relay so remote consumers don't hang
        with self._active_lock:
            active, self._active = self._active, {}
        for rid, ent in active.items():
            self._publish_final(rid, ent, RuntimeError(
                "FleetRouter stopped before this stream finished"))

    def _reply_topic(self, rid: str) -> str:
        return f"{self.prefix}.replies.{rid}"

    def _pump_loop(self):
        topic = f"{self.prefix}.requests"
        while self._running:
            try:
                data = self.transport.receive(topic, timeout=self.poll_s)
            except (TimeoutError, queue.Empty):
                continue
            except Exception:  # noqa: BLE001 — broker hiccup: keep serving
                log.exception("request-plane receive error (continuing)")
                time.sleep(self.poll_s)
                continue
            rid = None
            try:
                header, prompt = wire.decode_request(data)
                rid = header["request_id"]
                # rehydrate wire trace context: server-side spans land
                # under the CLIENT-minted trace id (one stitched
                # timeline per request across the wire)
                trace = None
                if header.get("trace_id") and monitor.is_enabled():
                    trace = RequestTrace(trace_id=header["trace_id"],
                                         remote=True,
                                         model=header["model"])
                stream = self.submit(
                    header["model"], prompt, header["n_tokens"],
                    temperature=header.get("temperature") or 0.0,
                    top_p=header.get("top_p"), rng=header.get("rng"),
                    trace=trace)
            except Exception as e:  # noqa: BLE001 — fail THAT request only
                if rid is not None:
                    try:
                        self.transport.send(
                            self._reply_topic(rid),
                            wire.encode_reply(rid, 0, None, done=True,
                                              error=e))
                    except Exception:  # noqa: BLE001 — the error-reply
                        # send is a broker touchpoint too: it failing
                        # must not kill the pump thread (the client
                        # times out instead — degraded, not dead)
                        log.exception("error-reply publish failed "
                                      "for %s", rid)
                else:
                    log.exception("undecodable request frame dropped")
                continue
            with self._active_lock:
                self._active[rid] = {"stream": stream, "cursor": 0,
                                     "seq": 0}

    def _relay_loop(self):
        while self._running:
            with self._active_lock:
                items = list(self._active.items())
            progressed = False
            for rid, ent in items:
                stream: TokenStream = ent["stream"]
                try:
                    # a chunk is FROZEN (tokens + seq) before its first
                    # send attempt and re-sent VERBATIM after a failed
                    # one: re-slicing the live token list under the
                    # same seq would combine with the client's seq
                    # dedup to silently drop whatever grew between the
                    # attempts
                    pend = ent.get("pending")
                    toks = stream.tokens
                    if pend is None and len(toks) > ent["cursor"]:
                        end = len(toks)
                        pend = ent["pending"] = (
                            ent["seq"], toks[ent["cursor"]:end], end)
                    if pend is not None:
                        seq, chunk, end = pend
                        self.transport.send(
                            self._reply_topic(rid),
                            wire.encode_reply(rid, seq, chunk,
                                              done=False,
                                              model=stream.model,
                                              version=stream.version))
                        # advance ONLY after a successful send
                        ent["pending"] = None
                        ent["cursor"] = end
                        ent["seq"] = seq + 1
                        progressed = True
                    # terminal frame: only once every token chunk is
                    # out, popped from _active only on a SUCCESSFUL
                    # send — the done frame is the one the client
                    # cannot make progress without, so it gets the
                    # same retry discipline as interior chunks (a
                    # transient error here retries next tick instead
                    # of stranding the client until its timeout)
                    if (stream._fut.done()
                            and ent.get("pending") is None
                            and ent["cursor"] == len(stream.tokens)):
                        exc = stream._fut.exception(timeout=0)
                        self.transport.send(
                            self._reply_topic(rid),
                            wire.encode_reply(
                                rid, ent["seq"], [], done=True,
                                model=stream.model,
                                version=stream.version, error=exc))
                        with self._active_lock:
                            self._active.pop(rid, None)
                        progressed = True
                except Exception:  # noqa: BLE001 — one stream's broker
                    # error must not kill the relay for every OTHER
                    # stream; this one retries next tick
                    log.exception("relay error for %s (will retry)",
                                  rid)
            if not progressed:
                time.sleep(self.poll_s)

    def _publish_final(self, rid: str, ent: dict,
                       exc: Optional[BaseException], tail=None):
        stream = ent["stream"]
        try:
            self.transport.send(
                self._reply_topic(rid),
                wire.encode_reply(rid, ent["seq"], tail or [], done=True,
                                  model=getattr(stream, "model", None),
                                  version=getattr(stream, "version", None),
                                  error=exc))
        except Exception:  # noqa: BLE001 — teardown must not throw
            log.exception("reply publish failed for %s", rid)


# ------------------------------------------------------- migrating stream
class MigratingStream:
    """One logical replica-served generation that SURVIVES worker death:
    wraps successive `ReplicaStream` attempts behind a single future
    face. When an attempt fails with `ReplicaLostError`, the tokens it
    delivered are committed, the dead replica is excluded, and the
    remainder resubmits through the router's balance-then-shed picker —
    verbatim to any survivor when nothing arrived, as prompt+received
    with ``emit_start`` on a same-version survivor when the stream was
    partial (the continuation contract: greedy rejoins bit-exactly,
    sampled keeps its fold_in chain because the rng key lives here, not
    on the replica). After `max_migrations` hops, or on any non-lost
    error, the failure surfaces unchanged."""

    def __init__(self, router, name: str, rset, prompt,
                 n_tokens: int, *, temperature: float = 0.0,
                 top_p=None, rng=None, trace=None,
                 max_migrations: int = 3):
        self._router = router
        self._rset = rset
        self.model = name
        self.request_id = uuid.uuid4().hex
        self._prompt = np.asarray(prompt)
        self.n_tokens = int(n_tokens)
        self._temperature = float(temperature)
        self._top_p = top_p
        self._rng = rng
        self.trace = trace
        self._fut: Future = Future()
        self._lock = threading.Lock()
        self._committed: List[int] = []
        self._cur = None
        self._dead: List[str] = []
        self._version_pin: Optional[int] = None
        self.max_migrations = int(max_migrations)
        self.migrations = 0
        self.version: Optional[int] = None
        self.replica: Optional[str] = None
        self.t_submit = time.monotonic()
        self._t_first: Optional[float] = None

    # ------------------------------------------------------------ consumer
    @property
    def tokens(self) -> List[int]:
        """Committed tokens from finished attempts plus the live
        attempt's stream so far — the one logical token list."""
        with self._lock:
            out = list(self._committed)
            cur = self._cur
        if cur is not None:
            out.extend(cur.tokens)
        return out

    @property
    def t_first(self) -> Optional[float]:
        if self._t_first is not None:
            return self._t_first
        cur = self._cur
        return None if cur is None else cur.t_first

    def done(self) -> bool:
        return self._fut.done()

    def result(self, timeout: Optional[float] = None) -> np.ndarray:
        return np.asarray(self._fut.result(timeout), np.int32)

    # ------------------------------------------------------------ internal
    def _bind(self, stream) -> None:
        with self._lock:
            self._cur = stream
        self.replica = stream.replica
        stream._fut.add_done_callback(
            lambda _f, s=stream: self._attempt_done(s))

    def _attempt_done(self, stream) -> None:
        if self._fut.done():
            return
        if self._t_first is None and stream.t_first is not None:
            self._t_first = stream.t_first
        exc = stream._fut.exception()
        if exc is None:
            with self._lock:
                self._committed.extend(stream.tokens)
                self._cur = None
                toks = list(self._committed)
            self.version = stream.version
            if self.trace is not None:
                self.trace.finish(status="ok")
            self._fut.set_result(toks)
            return
        if (not isinstance(exc, ReplicaLostError)
                or self.migrations >= self.max_migrations):
            if self.trace is not None:
                self.trace.finish(
                    status="shed" if isinstance(exc, ShedError)
                    else "error", error=type(exc).__name__)
            self._fut.set_exception(exc)
            return
        # ------------------------------------------------- migrate
        with self._lock:
            got = list(stream.tokens)
            self._committed.extend(got)
            self._cur = None
            n_done = len(self._committed)
        if self._committed and stream.version is not None:
            # a PARTIAL stream pins its version: the continuation's
            # numerics must come from the same weights
            self._version_pin = int(stream.version)
        if stream.replica is not None:
            self._dead.append(stream.replica)
        self.migrations += 1
        if self.trace is not None:
            self.trace.event("replica_migrate", lost=stream.replica,
                             committed=n_done, hop=self.migrations)
        if n_done >= self.n_tokens:
            # the worker emitted everything before dying — only the
            # terminal frame was lost
            self.version = stream.version
            self._fut.set_result(list(self._committed))
            return
        try:
            self._router._dispatch_replica(self)
        except Exception as e:  # noqa: BLE001 — resubmit failure is
            # THIS stream's terminal error (shed, nothing live, ...)
            if self.trace is not None:
                self.trace.finish(status="error",
                                  error=type(e).__name__)
            self._fut.set_exception(e)


# ------------------------------------------------------------------ client
class RemoteTokenStream:
    """Client face of one routed generation: iterate for token chunks
    as they arrive on the reply topic, or `result()` for the full
    array. Mirrors `TokenStream`'s two faces over the transport."""

    def __init__(self, transport, topic: str, *, timeout: float = 600.0,
                 trace: Optional[RequestTrace] = None):
        self.transport = transport
        self.topic = topic
        self.timeout = float(timeout)
        self.tokens = []
        self.model = None
        self.version = None
        # client half of the stitched timeline: same trace id as the
        # server-side spans (the wire's trace_id header field)
        self.trace = trace
        self.trace_id = None if trace is None else trace.trace_id
        self._got_first = False
        self._done = False
        self._error: Optional[BaseException] = None
        self._last_seq = -1

    def _pull(self, timeout: Optional[float] = None) -> np.ndarray:
        wait = self.timeout if timeout is None else timeout
        try:
            data = self.transport.receive(self.topic, timeout=wait)
        except queue.Empty as e:
            # LocalQueueTransport signals timeout as queue.Empty;
            # normalize so remote consumers see one timeout type
            raise TimeoutError(
                f"no reply on {self.topic} within {wait}s") from e
        header, chunk = wire.decode_reply(data)
        if header.get("model") is not None:
            self.model = header["model"]
            self.version = header["version"]
        # de-duplicate by seq: the relay retries a chunk whose send
        # failed AFTER the broker durably accepted it (at-least-once
        # transports — Kafka's flush can time out post-accept), so a
        # replayed ordinal must not extend the token array twice
        seq = int(header.get("seq", 0))
        if seq > self._last_seq:
            self._last_seq = seq
            self.tokens.extend(int(t) for t in chunk)
            if len(chunk) and not self._got_first:
                self._got_first = True
                if self.trace is not None:
                    self.trace.event("first_chunk")
        else:
            chunk = chunk[:0]
        if header["done"]:
            self._done = True
            self._error = wire.reply_error(header)
            tr = self.trace
            if tr is not None:
                tr.phase("remote_stream", tr.t_created,
                         time.perf_counter(), tokens=len(self.tokens))
                err = self._error
                tr.finish(status=("shed" if isinstance(err, ShedError)
                                  else "error" if err is not None
                                  else "ok"))
            # one reply topic per request: release its transport
            # resources (queue / Kafka consumer) the moment the
            # terminal frame lands, or a long-lived client leaks one
            # per finished request
            try:
                self.transport.close(self.topic)
            except Exception:  # noqa: BLE001 — cleanup is best-effort
                pass
        return chunk

    def __iter__(self):
        while not self._done:
            yield from (int(t) for t in self._pull())
        if self._error is not None:
            raise self._error

    def result(self, timeout: Optional[float] = None) -> np.ndarray:
        deadline = (None if timeout is None
                    else time.monotonic() + float(timeout))
        while not self._done:
            if deadline is None:
                self._pull()
            else:
                # each pull is bounded by the REMAINING deadline, not
                # the per-stream default — result(timeout=5) must
                # surface within ~5 s even when no reply ever arrives
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    raise TimeoutError(
                        f"no terminal reply on {self.topic}")
                self._pull(timeout=min(self.timeout, remaining))
        if self._error is not None:
            raise self._error
        return np.asarray(self.tokens, np.int32)


class FleetClient:
    """Submit generation requests over a `streaming.Transport` — no
    server reference, only topics. One client may serve many threads;
    each request gets its own reply topic keyed by request id."""

    def __init__(self, transport, prefix: str = "fleet"):
        self.transport = transport
        self.prefix = prefix

    def generate(self, model: str, prompt_ids, n_tokens: int, *,
                 temperature: float = 0.0, top_p: Optional[float] = None,
                 rng=None, request_id: Optional[str] = None,
                 timeout: float = 600.0,
                 trace_id: Optional[str] = None) -> RemoteTokenStream:
        rid = request_id or uuid.uuid4().hex
        # mint trace context client-side: the id crosses the wire and
        # the router/server spans stitch under it; the client keeps its
        # own wire-level trace on the same id
        trace = None
        if monitor.is_enabled():
            trace = RequestTrace(trace_id=trace_id, model=model,
                                 remote=False)
            trace.event("wire_submit", request_id=rid)
            trace_id = trace.trace_id
        self.transport.send(
            f"{self.prefix}.requests",
            wire.encode_request(model, rid, prompt_ids, n_tokens,
                                temperature=temperature, top_p=top_p,
                                rng=rng, trace_id=trace_id))
        stream = RemoteTokenStream(self.transport,
                                   f"{self.prefix}.replies.{rid}",
                                   timeout=timeout, trace=trace)
        stream.trace_id = trace_id
        return stream
