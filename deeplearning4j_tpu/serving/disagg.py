"""Disaggregated prefill/decode workers over the PFD handoff frame.

The dataflow-placement idea applied to inference: prefill is a
compute-bound burst (one bucketed whole-prompt pass), decode a
bandwidth-bound steady state (one token per dispatch reading every
weight) — different rooflines, so they can be DIFFERENT processes
wearing the same paged block table. `PrefillWorker` admits a prompt,
emits the first token, and exports the slot's granted K/V blocks +
host positions as a `DLFP` frame (`serving/wire.py`); `DecodeWorker`
adopts the frame into its own pool and continues the stream.

Parity contract: the adopted slot decodes bit-identically to the
colocated path — the K/V bytes are copied exactly (no recompute, no
cast) and the decode program is the same, so greedy streams match
whole-batch `generate()` token for token across the wire (the PR-9
contract extended; test- and loadtest-enforced).

Delivery is at-least-once: the exporting slot stays decodable until
the caller confirms the handoff landed (`PrefillWorker.prefill`
releases only after the frame bytes are built; a socket sender should
release only after the send succeeds).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import numpy as np

from deeplearning4j_tpu.serving import wire
from deeplearning4j_tpu.serving.engine import PagedDecodeEngine


class PrefillWorker:
    """The compute-bound half: admission waves only, every slot
    exported the moment its first token exists. Slots are transient —
    a prefill worker's pool holds each request only for the handoff
    window, so a small pool fronts a much larger decode fleet."""

    def __init__(self, net, *, n_slots: int = 8, n_blocks: int = 64,
                 block_len: int = 16, quantize: Optional[str] = None,
                 **engine_kw):
        self.engine = PagedDecodeEngine(
            net, n_slots=n_slots, n_blocks=n_blocks,
            block_len=block_len, quantize=quantize, **engine_kw)

    def prefill(self, prompt_ids, n_tokens: int, *,
                request_id: Optional[str] = None,
                temperature: float = 0.0,
                top_p: Optional[float] = None, rng=None,
                emit_start: int = 0) -> Tuple[int, Optional[bytes]]:
        """Run one prompt's prefill and package the handoff. Returns
        `(first_token, frame_bytes)`; `frame_bytes` is None when the
        request finished AT prefill (n_tokens == 1 — there is no
        decode half to hand off). Raises RuntimeError when the wave
        could not be admitted (slots/blocks exhausted — the caller's
        backpressure signal)."""
        req = dict(prompt_ids=np.asarray(prompt_ids), n_tokens=int(n_tokens),
                   request_id=request_id, temperature=temperature,
                   top_p=top_p, rng=rng, emit_start=emit_start)
        out = self.engine.admit_many([req])
        if not out:
            raise RuntimeError(
                "prefill worker could not admit the request "
                f"({self.engine.free_slots} slots, "
                f"{self.engine.free_blocks} blocks free)")
        slot, first, done = out[0]
        if done:
            return int(first), None
        header, kv = self.engine.export_handoff(slot)
        frame = wire.encode_handoff(header, kv)
        # frame built — the K/V bytes are out of the pool, release
        self.engine.evict(slot)
        return int(first), frame


class DecodeWorker:
    """The bandwidth-bound half: adopts handed-off slots and advances
    them one (or k speculative) token(s) per dispatch. Drive it with
    `step()` inside a scheduler, or `decode_to_completion` for
    whole-stream use (tests, the loadtest A/B)."""

    def __init__(self, net, *, n_slots: int = 8, n_blocks: int = 64,
                 block_len: int = 16, quantize: Optional[str] = None,
                 **engine_kw):
        self.engine = PagedDecodeEngine(
            net, n_slots=n_slots, n_blocks=n_blocks,
            block_len=block_len, quantize=quantize, **engine_kw)

    def adopt(self, frame: bytes) -> int:
        """Decode a `DLFP` frame and adopt its slot. Returns the local
        slot index; raises WireFormatError on corrupt bytes,
        ValueError/RuntimeError per `PagedDecodeEngine.adopt_handoff`."""
        header, kv = wire.decode_handoff(frame)
        return self.engine.adopt_handoff(header, kv)

    def step(self):
        """One decode dispatch across every adopted slot — the same
        `(emitted, finished)` contract as the engine."""
        return self.engine.step()

    def decode_to_completion(self, slots: List[int]) -> Dict[int, List[int]]:
        """Advance until every listed slot finishes; returns the
        decode-side token stream per slot (the full stream is the
        prefill's first token + this)."""
        out: Dict[int, List[int]] = {s: [] for s in slots}
        pending = set(slots)
        while pending:
            emitted, finished = self.engine.step()
            for s, toks in emitted.items():
                if s in out:
                    out[s].extend(toks)
            pending -= set(finished)
        return out


def run_disaggregated(prefill: PrefillWorker, decode: DecodeWorker,
                      prompts, n_tokens: int, *,
                      channel=None) -> List[List[int]]:
    """Run a batch of greedy requests through the split pipeline:
    prefill on one engine, PFD frames across `channel` (a connected
    socket pair — frames ride `wire.send_frame`/`recv_frame`; None
    keeps the bytes in-process, same encode/decode path), decode on
    the other. Returns the full token stream per prompt, directly
    comparable to the colocated/`generate()` reference."""
    firsts, frames = [], []
    for i, p in enumerate(prompts):
        first, frame = prefill.prefill(p, n_tokens, request_id=f"pfd-{i}")
        firsts.append(first)
        frames.append(frame)
    if channel is not None:
        tx, rx = channel
        delivered = []
        for frame in frames:
            if frame is None:
                delivered.append(None)
                continue
            wire.send_frame(tx, frame)
            delivered.append(wire.recv_frame(rx))
        frames = delivered
    slot_of = {}
    for i, frame in enumerate(frames):
        if frame is not None:
            slot_of[i] = decode.adopt(frame)
    rest = decode.decode_to_completion(list(slot_of.values()))
    return [[firsts[i]] + rest.get(slot_of[i], []) if i in slot_of
            else [firsts[i]] for i in range(len(prompts))]
