"""Paged continuous-batching decode engine.

The device-program half of the serving tier (the threaded scheduler
lives in serving/server.py): a fixed set of `n_slots` serving slots
advances ONE token per jitted dispatch over the paged KV pool — static
slot count means ONE XLA program no matter which sequences are in
flight; empty slots decode garbage into the reserved block and are
masked out on the host.

Per dispatch:

- `decode_step(params, state, kv, block_tables, token_ids, slot_state)
  -> (kv', next_ids, done_flags)` — embedding -> per-slot positional
  signal -> paged transformer blocks -> per-position softmax, then
  greedy argmax or per-slot sampled next token. Inputs ride h2d once
  per step (they are a few `[S]` vectors + the `[S, max_blocks]`
  tables); the pools stay device-resident (donated where the backend
  supports it).
- admission prefills a WAVE of prompts — heterogeneous lengths
  bucket-padded to one shape (`zoo.transformer.get_prefill_bucketed`,
  per-slot last-position gather) — then scatters the filled monolithic
  carries into each sequence's pool blocks. Prefill numerics are
  `generate()`'s by construction; right padding is sound because the
  blocks are causal and every read past a slot's position is masked.

Block allocation (`allocation="incremental"`, the default): admission
grants only the blocks the PROMPT occupies; `step()` grows a slot's
block table lazily as its position crosses block boundaries. Under
pool pressure the lowest-progress slot is evicted and handed back to
the scheduler for requeue (`drain_preempted`) instead of deadlocking —
effective concurrency rises ~budget/actual_length for short
generations at the same pool size. `allocation="upfront"` restores the
PR-9 grant-everything-at-admission behavior (the A/B baseline the
concurrency tests compare against).

Weights (`quantize="int8"`): the decode/prefill/admission programs
read per-output-channel int8 matmul weights (nd/quant.py) from HBM and
compute in the policy's compute dtype — autoregressive decode is
bandwidth-bound, so the ~4x weight-byte cut is the serving throughput
lever. `net.params` (the training master) is untouched.

Decode-parity contract (docs/SERVING.md): for the same prompt and
sampling config, the token stream is identical to whole-batch
`generate()` — greedy is exact (test-enforced bit-equality; with
`quantize=` the reference is `generate(quantize=...)`); sampled mode
derives token t's key as `fold_in(request_key, t)`, which makes a
request's stream deterministic REGARDLESS of what else is in flight —
including across a preempt-and-requeue, whose continuation re-admits
at the same emit offset.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.nd import quant
from deeplearning4j_tpu.nd.donation import donate_argnums
from deeplearning4j_tpu.nn.layers.recurrent import BaseRecurrentLayer
from deeplearning4j_tpu.nn.layers.transformer import (
    PositionalEncodingLayer,
    TransformerEncoderBlock,
    stream_budget,
)
from deeplearning4j_tpu.serving.paged import (
    GARBAGE_BLOCK,
    PagedKVPool,
    blocks_needed,
)


def bucket_len(n: int, cap: int) -> int:
    """Pad length for mixed-length prefill: the next power of two >= n,
    clamped to `cap` (the stream budget). Quantized lengths bound the
    prefill program grid exactly like power-of-two wave widths bound
    the admission programs."""
    b = 1
    while b < n:
        b *= 2
    return min(b, cap)


class Slot:
    """Host mirror of one serving slot's in-flight sequence."""

    __slots__ = ("request_id", "blocks", "prompt_len", "n_tokens",
                 "emitted", "pos", "emit_base")

    def __init__(self, request_id, blocks, prompt_len, n_tokens,
                 emit_base=0):
        self.request_id = request_id
        self.blocks = blocks
        self.prompt_len = prompt_len
        self.n_tokens = n_tokens
        self.emitted = 0
        self.pos = prompt_len
        # tokens the request emitted in EARLIER admissions (a requeued
        # continuation) — progress ordering and the sampled-rng emit
        # offset both count from here
        self.emit_base = emit_base

    @property
    def progress(self) -> int:
        """Total tokens this REQUEST has emitted (across preemptions)
        — the eviction policy's ordering key."""
        return self.emit_base + self.emitted


class PagedDecodeEngine:
    """Continuous-batching decode over a `PagedKVPool`.

    Synchronous and single-threaded by design — every method must be
    called from one scheduler thread (serving/server.py owns that
    thread; tests drive the engine directly for determinism).

    `top_k` is engine-static (lax.top_k needs a static k — same
    constraint `generate()` documents); temperature and top_p are
    per-request traced values, so mixed greedy/sampled batches share
    the one decode program.
    """

    def __init__(self, net, *, n_slots: int = 8, n_blocks: int = 64,
                 block_len: int = 16, top_k: Optional[int] = None,
                 steps_per_dispatch: int = 1,
                 quantize: Optional[str] = None,
                 allocation: str = "incremental"):
        if not getattr(net, "_initialized", False):
            net.init()
        self.net = net
        self.n_slots = int(n_slots)
        self.steps_per_dispatch = int(steps_per_dispatch)
        if self.steps_per_dispatch < 1:
            raise ValueError(
                f"steps_per_dispatch must be >= 1; got {steps_per_dispatch}")
        self.top_k = None if top_k is None else int(top_k)
        if self.n_slots < 1:
            raise ValueError(f"n_slots must be >= 1; got {n_slots}")
        if allocation not in ("incremental", "upfront"):
            raise ValueError(
                f"allocation must be 'incremental' or 'upfront'; "
                f"got {allocation!r}")
        self.allocation = allocation
        self.quantize = quantize
        # pay the quantization pass NOW, not inside the first live
        # dispatch (the tree itself is resolved per dispatch — see
        # the _params property)
        quant.serving_params(net, quantize)
        budget = stream_budget(net.layers)
        if budget is None:
            raise ValueError(
                "net has no bounded stream budget (no TransformerEncoder"
                "Block / PositionalEncodingLayer) — nothing to page")
        if budget % block_len != 0:
            raise ValueError(
                f"block_len {block_len} must divide the stream budget "
                f"{budget} (KV cache_len / positional max_len): the "
                f"gathered page view must have the same length as the "
                f"monolithic cache for decode parity")
        vocab = getattr(net.layers[-1], "n_out", None)
        if self.top_k is not None and not (1 <= self.top_k <=
                                           (vocab or self.top_k)):
            raise ValueError(f"top_k must be in [1, vocab={vocab}]; "
                             f"got {top_k}")
        self.max_blocks = budget // int(block_len)
        self.max_total_tokens = budget
        self.pool = PagedKVPool(net, n_blocks, block_len)
        self.block_len = int(block_len)
        # a serving "plan": how each layer participates in the paged
        # decode walk. Input preprocessors would silently change the
        # math mid-walk — reject loudly (the zoo LMs have none).
        if net.conf.input_preprocessors:
            raise ValueError(
                "paged decode does not support input preprocessors "
                f"(found at {sorted(net.conf.input_preprocessors)})")
        self._plan: List[Tuple] = []
        pool_j = 0
        for i, layer in enumerate(net.layers):
            if isinstance(layer, TransformerEncoderBlock):
                self._plan.append(("block", i, pool_j))
                pool_j += 1
            elif isinstance(layer, PositionalEncodingLayer):
                self._plan.append(("pos", i))
            elif isinstance(layer, BaseRecurrentLayer):
                raise ValueError(
                    f"layer {i} ({type(layer).__name__}) carries "
                    "recurrent state but has no paged decode path")
            else:
                self._plan.append(("plain", i))
        # host slot state (uploaded per step; a few [S] vectors)
        S = self.n_slots
        self.block_tables = np.zeros((S, self.max_blocks), np.int32)
        self.pos = np.zeros(S, np.int32)
        self.active = np.zeros(S, bool)
        self.remaining = np.zeros(S, np.int32)
        self.emit_idx = np.zeros(S, np.int32)
        self.last_token = np.zeros(S, np.int32)
        self.keys = np.zeros((S, 2), np.uint32)
        self.temp = np.zeros(S, np.float32)
        self.top_p = np.ones(S, np.float32)
        self.slots: List[Optional[Slot]] = [None] * S
        self._decode_full = None      # greedy + sampling chain
        self._decode_greedy = None    # argmax only (no sort/rng ops)
        self._admit_finish = {}       # k -> fused write-pages+first-token
        # allocator observability (host ints — the scheduler mirrors
        # them onto the metrics registry) + preemption notices the
        # scheduler drains for requeue
        self.block_grants_total = 0
        self.evict_requeue_total = 0
        self._preempted: List[dict] = []

    # ------------------------------------------------------------ queries
    @property
    def _params(self):
        """The params tree every serving program reads: int8-quantized
        matmul weights under quantize="int8" (nd/quant.py), the net's
        own tree otherwise — resolved PER DISPATCH, so a fit()/restore
        between dispatches serves the fresh weights (serving_params'
        identity-keyed cache makes this a dict lookup; quantization
        re-runs only when net.params was reassigned)."""
        return quant.serving_params(self.net, self.quantize)

    @property
    def free_slots(self) -> int:
        return sum(1 for s in self.slots if s is None)

    @property
    def active_slots(self) -> int:
        return int(self.active.sum())

    @property
    def free_blocks(self) -> int:
        return self.pool.free_blocks

    def _admit_blocks(self, prompt_len: int, n_tokens: int) -> int:
        """Blocks an admission grants NOW: the prompt's footprint under
        incremental allocation (decode growth is lazy), the request's
        whole budget under the PR-9 upfront policy."""
        if self.allocation == "incremental":
            return blocks_needed(prompt_len, self.block_len)
        return blocks_needed(prompt_len + n_tokens, self.block_len)

    def can_admit(self, prompt_len: int, n_tokens: int) -> bool:
        return (any(s is None for s in self.slots)
                and self._admit_blocks(prompt_len, n_tokens)
                <= self.pool.free_blocks)

    def check_budget(self, prompt_len: int, n_tokens: int):
        """Reject requests that can NEVER be admitted — distinct from
        `can_admit` (not right now): over the per-sequence page budget,
        or needing more blocks AT THE END than the whole pool owns
        (under incremental allocation a request must still be able to
        finish alone in the pool — pool-pressure preemption can evict
        every OTHER slot, never conjure capacity)."""
        total = prompt_len + n_tokens
        if n_tokens < 1:
            raise ValueError(f"n_tokens must be >= 1; got {n_tokens}")
        if total > self.max_total_tokens:
            raise ValueError(
                f"prompt ({prompt_len}) + n_tokens ({n_tokens}) = {total} "
                f"exceeds the per-sequence page budget "
                f"{self.max_total_tokens} (max_blocks {self.max_blocks} x "
                f"block_len {self.block_len}); this request can never be "
                f"admitted — rebuild the model with a larger max_len")
        usable = self.pool.n_blocks - 1      # id 0 is the garbage block
        if blocks_needed(total, self.block_len) > usable:
            raise ValueError(
                f"request needs {blocks_needed(total, self.block_len)} "
                f"pool blocks but the pool only has {usable} usable "
                f"(n_blocks {self.pool.n_blocks} incl. the reserved "
                f"garbage block); it can never be admitted — grow "
                f"n_blocks or shorten the request")

    # ----------------------------------------------------------- sampling
    def _sample_ids(self, probs, keys, emit_idx, temp, top_p,
                    greedy_only: bool = False):
        """Next token per row of `probs` [S, V]: greedy argmax where
        temp == 0 (bit-identical to `generate(temperature=0)`), else
        the same log/clip/filter/categorical chain `generate` runs —
        with a PER-SLOT key folded by emit index, the serving rng
        contract. `greedy_only=True` (a STATIC program variant the
        scheduler picks when no sampled request is in flight) skips
        the sort/threefry chain entirely — measured at ~half the
        decode chunk on the CPU sandbox."""
        greedy_ids = jnp.argmax(probs, axis=-1).astype(jnp.int32)
        if greedy_only:
            return greedy_ids
        from deeplearning4j_tpu.zoo.transformer import filter_logits
        safe_t = jnp.where(temp > 0, temp, 1.0)
        logits = jnp.log(jnp.clip(probs, 1e-9, None)) / safe_t[:, None]
        # generate()'s own filter body, with per-slot traced p
        # (p=1.0 keeps everything)
        logits = filter_logits(logits, self.top_k, top_p[:, None])
        skeys = jax.vmap(jax.random.fold_in)(keys, emit_idx)
        sampled = jax.vmap(jax.random.categorical)(skeys, logits)
        return jnp.where(temp > 0, sampled.astype(jnp.int32), greedy_ids)

    # ------------------------------------------------------ jit builders
    def _decode_body(self, greedy_only: bool):
        """The decode-chunk python body (jitted by `_build_decode`;
        traced directly by `decode_cost_report` for the byte-table
        evidence)."""
        net, layers, plan = self.net, self.net.layers, self._plan
        J = self.steps_per_dispatch

        def one_token(params, state, kv, block_tables, token_ids, pos,
                      keys, emit_idx, temp, top_p):
            h = token_ids[:, None]            # [S, 1] int ids
            kv = list(kv)
            for entry in plan:
                kind, i = entry[0], entry[1]
                layer = layers[i]
                lp = params.get(str(i), {})
                ls = state.get(str(i), {})
                if kind == "plain":
                    h, _ = layer.forward(lp, ls, h, train=False, rng=None)
                elif kind == "pos":
                    h, _ = layer.forward_at_positions(lp, ls, h, pos)
                else:
                    j = entry[2]
                    k_pool, v_pool = kv[j]
                    h, k_pool, v_pool = layer.forward_paged(
                        lp, h, k_pool, v_pool, block_tables, pos)
                    kv[j] = (k_pool, v_pool)
            probs = h[:, -1]                   # [S, V]
            return tuple(kv), self._sample_ids(probs, keys, emit_idx,
                                               temp, top_p,
                                               greedy_only=greedy_only)

        def decode_step(params, state, kv, block_tables, token_ids,
                        pos, remaining, keys, emit_idx, temp, top_p):
            """`steps_per_dispatch` micro-steps fused into ONE program
            via lax.scan: host round-trip and dispatch overhead
            amortize over J tokens x S slots (the continuous-batching
            counterpart of `generate()`'s fused decode scan). A slot
            finishing mid-chunk keeps decoding — into its own pages or
            the garbage block, never another slot's — and the `valids`
            mask tells the host which emissions are real. J=1 is the
            admit-every-token schedule the scheduler defaults to."""
            params = net.dtype.cast_params(params)

            def micro(carry, _):
                kv, tok, pos, rem, emit = carry
                kv, nxt = one_token(params, state, kv, block_tables,
                                    tok, pos, keys, emit, temp, top_p)
                return ((kv, nxt, pos + 1, rem - 1, emit + 1),
                        (nxt, rem > 0))

            carry = (kv, token_ids, pos, remaining, emit_idx)
            (kv, _, _, _, _), (toks, valids) = jax.lax.scan(
                micro, carry, None, length=J)
            return kv, toks, valids            # [J, S] each

        return decode_step

    def _build_decode(self, greedy_only: bool):
        return jax.jit(self._decode_body(greedy_only),
                       donate_argnums=donate_argnums(2))

    def decode_cost_report(self) -> dict:
        """Byte accounting of the REAL decode program (greedy variant)
        via the hlo_cost per-op tables — the quantization ledger's
        evidence seam: weight HBM bytes of the params tree the program
        reads, split matmul-weights vs total, plus the per-op
        operand+result byte totals of one traced decode chunk."""
        from benchtools import hlo_cost

        S = self.n_slots
        args = (self._params, self.net.net_state, self.pool.kv,
                jnp.asarray(self.block_tables), jnp.asarray(self.last_token),
                jnp.asarray(self.pos), jnp.asarray(self.remaining),
                jnp.asarray(self.keys), jnp.asarray(self.emit_idx),
                jnp.asarray(self.temp), jnp.asarray(self.top_p))
        jaxpr = jax.make_jaxpr(self._decode_body(greedy_only=True))(*args)
        table = hlo_cost.per_op_table(jaxpr,
                                      fused_steps=self.steps_per_dispatch)
        mm_keys = quant.quantized_weight_keys(self.net)
        mm_bytes = quant.weight_bytes(
            {lk: {pk: self._params[lk][pk] for pk in pks}
             for lk, pks in mm_keys.items()})
        return {
            "quantize": self.quantize,
            "weight_bytes": quant.weight_bytes(self._params),
            "matmul_weight_bytes": mm_bytes,
            "decode_bytes_per_step": table["total_bytes_per_step"],
            "decode_flops_per_step": table["total_flops_per_step"],
            "n_slots": S,
        }

    def _build_admit_finish(self, k: int, greedy_only: bool):
        """One fused dispatch completing a k-wide admission wave:
        scatter every sequence's monolithic prefill K/V into its pool
        pages AND sample the wave's first tokens from the prefill
        probs. Separate per-request dispatches here were measured to
        cost as much as a whole `generate()` call each on the CPU
        sandbox — admission overhead is exactly what the sequential
        baseline pays, so it must be amortized for continuous batching
        to win."""
        bl = self.block_len

        def admit_finish(kv, rows, block_carries, probs, keys, emit0,
                         temp, top_p):
            # rows [k, max_rows]; block_carries: per layer (k_cache,
            # v_cache) with leading dim k; probs [k, V]; emit0 [k] is
            # the sampled-rng emit offset (nonzero for a requeued
            # continuation — its stream keeps the fold_in(key, t)
            # indices it would have had uninterrupted)
            out = []
            for (k_pool, v_pool), (k_cache, v_cache) in zip(
                    kv, block_carries):
                C = k_cache.shape[1]
                shape = (k * (C // bl), bl) + k_cache.shape[2:]
                flat_rows = rows[:, :C // bl].reshape(-1)
                out.append((
                    k_pool.at[flat_rows].set(
                        k_cache.reshape(shape).astype(k_pool.dtype)),
                    v_pool.at[flat_rows].set(
                        v_cache.reshape(shape).astype(v_pool.dtype)),
                ))
            firsts = self._sample_ids(probs, keys, emit0, temp, top_p,
                                      greedy_only=greedy_only)
            return tuple(out), firsts

        return jax.jit(admit_finish, donate_argnums=donate_argnums(0))

    # ---------------------------------------------------------- admission
    def admit(self, prompt_ids, n_tokens: int, *, request_id=None,
              temperature: float = 0.0, top_p: Optional[float] = None,
              rng=None):
        """Single-request admission (a k=1 `admit_many` wave). Returns
        (slot index, first emitted token, done) or None when capacity
        can't take the request right now."""
        out = self.admit_many([dict(prompt_ids=prompt_ids,
                                    n_tokens=n_tokens,
                                    request_id=request_id,
                                    temperature=temperature,
                                    top_p=top_p, rng=rng)])
        return out[0] if out else None

    def admit_many(self, requests: List[dict]):
        """Admission wave: prefill up to len(requests) prompts — of
        HETEROGENEOUS lengths, right-padded to one power-of-two bucket
        — as one batch through the cached bucketed-prefill jit
        (zoo/transformer.get_prefill_bucketed: `generate()`'s forward
        with a per-slot last-position gather, so prefill numerics are
        its by construction), then one fused dispatch writes all their
        pool pages and samples all their first tokens. Requests beyond
        the wave's slot/block capacity are left unadmitted (the
        returned list is a PREFIX of the input — FIFO order
        preserved).

        Each request dict: prompt_ids, n_tokens, and optionally
        request_id, temperature, top_p, rng, emit_start (a requeued
        continuation's already-emitted token count — offsets the
        sampled-rng fold and the progress ordering). Returns
        [(slot, first_token, done), ...] for the admitted prefix."""
        if not requests:
            return []
        wave = []
        try:
            for r in requests:
                prompt = np.asarray(r["prompt_ids"])
                if prompt.ndim == 2 and prompt.shape[0] == 1:
                    prompt = prompt[0]
                if prompt.ndim != 1 or prompt.size == 0:
                    raise ValueError(
                        f"prompt must be a non-empty 1-D id sequence; "
                        f"got shape {prompt.shape}")
                P = int(prompt.shape[0])
                n_tokens = int(r["n_tokens"])
                self.check_budget(P, n_tokens)
                slot = next((i for i, s in enumerate(self.slots)
                             if s is None
                             and all(i != w[0] for w in wave)),
                            None)
                if slot is None:
                    break
                nb = self._admit_blocks(P, n_tokens)
                blocks = self.pool.allocator.allocate(nb)
                if blocks is None:
                    break
                wave.append((slot, prompt, n_tokens, nb, blocks, r))
            if not wave:
                return []
            return self._admit_wave(wave)
        except Exception:
            # a mid-wave failure (validation of a later request, a
            # prefill/admit dispatch error) must return the wave's
            # already-allocated blocks — no Slot owns them yet, so
            # _release could never recover them and the pool would
            # shrink permanently (capacity leak -> eventual silent
            # starvation of every later admission). Entries a Slot DID
            # take ownership of (partial bookkeeping) keep theirs —
            # the normal release path frees those.
            for slot, _, _, _, blocks, _ in wave:
                s = self.slots[slot]
                if s is None or s.blocks is not blocks:
                    try:
                        self.pool.allocator.free(blocks)
                    except ValueError:
                        pass   # already back in the pool
            raise

    def _admit_wave(self, wave):
        k = len(wave)
        # pad the wave WIDTH to the next power of two: every distinct
        # batch width costs a prefill + admit_finish COMPILE, and
        # free-slot counts vary chunk to chunk — unquantized widths
        # were measured as a compile storm that dwarfed the serving
        # itself. Dummy rows repeat the last prompt, scatter only into
        # the garbage block, and their sampled firsts are discarded.
        k2 = 1
        while k2 < k:
            k2 *= 2
        # pad the prompt LENGTHS to one power-of-two bucket (mixed-
        # length waves — the same-length restriction serialized
        # admissions under realistic traffic): right padding is sound
        # because the blocks are causal and the padding rows' K/V land
        # past each slot's position, where every later read masks them
        Pb = bucket_len(max(int(w[1].shape[0]) for w in wave),
                        self.max_total_tokens)

        net = self.net
        from deeplearning4j_tpu.zoo.transformer import get_prefill_bucketed
        prefill = get_prefill_bucketed(net)
        carries = {str(i): layer.init_carry(k2, net.dtype.compute_dtype)
                   for i, layer in enumerate(net.layers)
                   if isinstance(layer, BaseRecurrentLayer)}
        prompts = np.zeros((k2, Pb), np.int32)
        last_idx = np.zeros(k2, np.int32)
        for j, w in enumerate(wave):
            prompts[j, :w[1].shape[0]] = w[1]
            last_idx[j] = w[1].shape[0] - 1
        for j in range(k, k2):                # dummy width-padding rows
            prompts[j] = prompts[k - 1]
            last_idx[j] = last_idx[k - 1]
        probs, carries = prefill(self._params, net.net_state,
                                 jnp.asarray(prompts), carries,
                                 jnp.asarray(last_idx))

        block_carries = [carries[str(i)] for i in self.pool.layer_indices]
        max_rows = max(c[0].shape[1] // self.block_len
                       for c in block_carries)
        rows = np.full((k2, max_rows), GARBAGE_BLOCK, np.int32)
        keys = np.zeros((k2, 2), np.uint32)
        emit0 = np.zeros(k2, np.int32)
        temps = np.zeros(k2, np.float32)
        top_ps = np.ones(k2, np.float32)
        for j, (slot, prompt, n_tokens, nb, blocks, r) in enumerate(wave):
            rows[j, :nb] = blocks
            if r.get("rng") is not None:
                keys[j] = np.asarray(r["rng"], np.uint32).reshape(2)
            emit0[j] = int(r.get("emit_start") or 0)
            temps[j] = r.get("temperature") or 0.0
            p = r.get("top_p")
            top_ps[j] = 1.0 if p is None else p
        # all-greedy waves skip the sampling chain (sort + threefry) on
        # the TTFT-critical path — same static-variant split the
        # decode program uses
        greedy = not bool((temps > 0).any())
        fin = self._admit_finish.get((k2, greedy))
        if fin is None:
            fin = self._admit_finish[(k2, greedy)] = \
                self._build_admit_finish(k2, greedy)
        self.pool.kv, firsts = fin(
            self.pool.kv, jnp.asarray(rows),
            tuple((c[0], c[1]) for c in block_carries), probs,
            jnp.asarray(keys), jnp.asarray(emit0), jnp.asarray(temps),
            jnp.asarray(top_ps))
        firsts = np.asarray(firsts)

        out = []
        for j, (slot, prompt, n_tokens, nb, blocks, r) in enumerate(wave):
            first = int(firsts[j])
            done = n_tokens == 1
            self.slots[slot] = Slot(r.get("request_id"), blocks,
                                    len(prompt), n_tokens,
                                    emit_base=int(emit0[j]))
            self.slots[slot].emitted = 1
            self.block_tables[slot] = GARBAGE_BLOCK
            self.block_tables[slot, :nb] = blocks
            self.pos[slot] = len(prompt)
            self.remaining[slot] = n_tokens - 1
            self.emit_idx[slot] = int(emit0[j]) + 1
            self.last_token[slot] = first
            self.keys[slot] = keys[j]
            self.temp[slot] = temps[j]
            self.top_p[slot] = top_ps[j]
            self.active[slot] = not done
            self.block_grants_total += nb
            if done:
                self._release(slot)
            out.append((slot, first, done))
        return out

    # -------------------------------------------- incremental block grants
    def _lowest_progress_active(self) -> int:
        """The pool-pressure eviction victim: the active slot whose
        REQUEST has emitted the fewest tokens (requeue costs it the
        least re-prefill work). Ties break toward the higher slot
        INDEX — an arbitrary but deterministic order (slot index is
        not admission order once retired slots are reused)."""
        best, best_p = -1, None
        for i in np.flatnonzero(self.active):
            i = int(i)
            p = self.slots[i].progress
            if best_p is None or p <= best_p:
                best, best_p = i, p
        return best

    def _preempt(self, slot: int):
        s = self.slots[slot]
        self._preempted.append({
            "slot": slot, "request_id": s.request_id,
            "emitted": s.progress,
        })
        self.evict_requeue_total += 1
        self._release(slot)

    def drain_preempted(self) -> List[dict]:
        """Preemption notices since the last drain: [{slot, request_id,
        emitted}] — the scheduler requeues each request as a
        continuation (prompt + its emitted tokens, emit_start set) at
        the head of the admission queue."""
        out, self._preempted = self._preempted, []
        return out

    def _grow_block_tables(self):
        """Lazy block grants before a decode dispatch: every active
        slot gets the blocks the chunk's writes will cross into. Under
        pool pressure the lowest-progress slot is evicted (requeue, not
        deadlock); eviction frees at least one block per round, and
        check_budget guarantees a slot left alone in the pool can
        always finish — so this terminates with every surviving slot
        fully granted."""
        J = self.steps_per_dispatch
        for s in range(self.n_slots):
            if not self.active[s] or self.slots[s] is None:
                continue
            slot = self.slots[s]
            tokens = min(J, int(self.remaining[s]))
            needed = blocks_needed(int(self.pos[s]) + tokens,
                                   self.block_len)
            have = len(slot.blocks)
            if needed <= have:
                continue
            got = self.pool.allocator.allocate(needed - have)
            while got is None:
                victim = self._lowest_progress_active()
                self._preempt(victim)
                if victim == s:
                    break              # s itself lost the pool race
                got = self.pool.allocator.allocate(needed - have)
            if got is None or self.slots[s] is None:
                continue
            slot.blocks.extend(got)
            self.block_tables[s, have:needed] = got
            self.block_grants_total += len(got)

    # ------------------------------------------------------------- decode
    def step(self) -> Tuple[Dict[int, List[int]], List[int]]:
        """One continuous-batching dispatch: every active slot advances
        up to `steps_per_dispatch` tokens. Returns ({slot: [tokens
        emitted this dispatch]}, [slots that finished and were
        released]). Under incremental allocation, slots whose next
        writes cross a block boundary are granted blocks first — and
        pool pressure preempts the lowest-progress slot into
        `drain_preempted()` instead of deadlocking."""
        if self.allocation == "incremental":
            self._grow_block_tables()
        if not self.active.any():
            return {}, []
        # two static program variants: the greedy-only decode skips the
        # sampling chain (sort + threefry) — picked whenever no sampled
        # request is in flight, the common serving case
        if (self.temp[self.active] > 0).any():
            if self._decode_full is None:
                self._decode_full = self._build_decode(greedy_only=False)
            decode = self._decode_full
        else:
            if self._decode_greedy is None:
                self._decode_greedy = self._build_decode(greedy_only=True)
            decode = self._decode_greedy
        kv, toks, valids = decode(
            self._params, self.net.net_state, self.pool.kv,
            jnp.asarray(self.block_tables), jnp.asarray(self.last_token),
            jnp.asarray(self.pos), jnp.asarray(self.remaining),
            jnp.asarray(self.keys), jnp.asarray(self.emit_idx),
            jnp.asarray(self.temp), jnp.asarray(self.top_p))
        self.pool.kv = kv
        toks = np.asarray(toks)                     # [J, S]
        valids = np.asarray(valids)
        taken = valids.sum(axis=0).astype(np.int32)  # [S] tokens emitted
        act = self.active
        last_idx = np.clip(taken - 1, 0, None)
        self.last_token = np.where(
            act & (taken > 0), toks[last_idx, np.arange(toks.shape[1])],
            self.last_token)
        self.pos = self.pos + np.where(act, taken, 0)
        self.emit_idx = self.emit_idx + np.where(act, taken, 0)
        self.remaining = self.remaining - np.where(act, taken, 0)
        emitted: Dict[int, List[int]] = {}
        finished = []
        for i in np.flatnonzero(act):
            i = int(i)
            emitted[i] = [int(t) for t in toks[valids[:, i], i]]
            self.slots[i].emitted += int(taken[i])
            self.slots[i].pos = int(self.pos[i])
            if self.remaining[i] <= 0:
                finished.append(i)
                self._release(i)
        return emitted, finished

    # ------------------------------------------------------------ evict
    def evict(self, slot: int):
        """Mid-stream eviction (cancel/timeout): free the slot and its
        blocks immediately; the pool pages become garbage the moment
        the table row is retired (no device work — the next gather by
        a reusing sequence overwrites them via its own prefill)."""
        if self.slots[slot] is None:
            raise ValueError(f"slot {slot} is not in use")
        self._release(slot)

    def _release(self, slot: int):
        s = self.slots[slot]
        self.pool.allocator.free(s.blocks)
        self.slots[slot] = None
        self.active[slot] = False
        self.remaining[slot] = 0
        self.block_tables[slot] = GARBAGE_BLOCK
