"""Paged continuous-batching decode engine.

The device-program half of the serving tier (the threaded scheduler
lives in serving/server.py): a fixed set of `n_slots` serving slots
advances ONE token per jitted dispatch over the paged KV pool — static
slot count means ONE XLA program no matter which sequences are in
flight; empty slots decode garbage into the reserved block and are
masked out on the host.

Per dispatch:

- `decode_step(params, state, kv, block_tables, token_ids, slot_state)
  -> (kv', next_ids, done_flags)` — embedding -> per-slot positional
  signal -> paged transformer blocks -> per-position softmax, then
  greedy argmax or per-slot sampled next token. Inputs ride h2d once
  per step (they are a few `[S]` vectors + the `[S, max_blocks]`
  tables); the pools stay device-resident (donated where the backend
  supports it).
- admission prefills a WAVE of prompts — heterogeneous lengths
  bucket-padded to one shape (`zoo.transformer.get_prefill_bucketed`,
  per-slot last-position gather) — then scatters the filled monolithic
  carries into each sequence's pool blocks. Prefill numerics are
  `generate()`'s by construction; right padding is sound because the
  blocks are causal and every read past a slot's position is masked.

Block allocation (`allocation="incremental"`, the default): admission
grants only the blocks the PROMPT occupies; `step()` grows a slot's
block table lazily as its position crosses block boundaries. Under
pool pressure the lowest-progress slot is evicted and handed back to
the scheduler for requeue (`drain_preempted`) instead of deadlocking —
effective concurrency rises ~budget/actual_length for short
generations at the same pool size. `allocation="upfront"` restores the
PR-9 grant-everything-at-admission behavior (the A/B baseline the
concurrency tests compare against).

Weights (`quantize="int8"`): the decode/prefill/admission programs
read per-output-channel int8 matmul weights (nd/quant.py) from HBM and
compute in the policy's compute dtype — autoregressive decode is
bandwidth-bound, so the ~4x weight-byte cut is the serving throughput
lever. `net.params` (the training master) is untouched.

Decode-parity contract (docs/SERVING.md): for the same prompt and
sampling config, the token stream is identical to whole-batch
`generate()` — greedy is exact (test-enforced bit-equality; with
`quantize=` the reference is `generate(quantize=...)`); sampled mode
derives token t's key as `fold_in(request_key, t)`, which makes a
request's stream deterministic REGARDLESS of what else is in flight —
including across a preempt-and-requeue, whose continuation re-admits
at the same emit offset.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.nd import quant
from deeplearning4j_tpu.nd.donation import donate_argnums
from deeplearning4j_tpu.nn.layers.recurrent import BaseRecurrentLayer
from deeplearning4j_tpu.nn.layers.transformer import (
    PositionalEncodingLayer,
    TransformerEncoderBlock,
    stream_budget,
)
from deeplearning4j_tpu.serving.paged import (
    GARBAGE_BLOCK,
    PagedKVPool,
    RadixPrefixCache,
    blocks_needed,
)


def bucket_len(n: int, cap: int) -> int:
    """Pad length for mixed-length prefill: the next power of two >= n,
    clamped to `cap` (the stream budget). Quantized lengths bound the
    prefill program grid exactly like power-of-two wave widths bound
    the admission programs."""
    b = 1
    while b < n:
        b *= 2
    return min(b, cap)


class Slot:
    """Host mirror of one serving slot's in-flight sequence."""

    __slots__ = ("request_id", "blocks", "prompt_len", "n_tokens",
                 "emitted", "pos", "emit_base", "history")

    def __init__(self, request_id, blocks, prompt_len, n_tokens,
                 emit_base=0, history=None):
        self.request_id = request_id
        self.blocks = blocks
        self.prompt_len = prompt_len
        self.n_tokens = n_tokens
        self.emitted = 0
        self.pos = prompt_len
        # tokens the request emitted in EARLIER admissions (a requeued
        # continuation) — progress ordering and the sampled-rng emit
        # offset both count from here
        self.emit_base = emit_base
        # full token history (prompt + every emitted token): the
        # self-drafting proposer's n-gram suffix cache reads it
        self.history: List[int] = history if history is not None else []

    @property
    def progress(self) -> int:
        """Total tokens this REQUEST has emitted (across preemptions)
        — the eviction policy's ordering key."""
        return self.emit_base + self.emitted


class PagedDecodeEngine:
    """Continuous-batching decode over a `PagedKVPool`.

    Synchronous and single-threaded by design — every method must be
    called from one scheduler thread (serving/server.py owns that
    thread; tests drive the engine directly for determinism).

    `top_k` is engine-static (lax.top_k needs a static k — same
    constraint `generate()` documents); temperature and top_p are
    per-request traced values, so mixed greedy/sampled batches share
    the one decode program.
    """

    def __init__(self, net, *, n_slots: int = 8, n_blocks: int = 64,
                 block_len: int = 16, top_k: Optional[int] = None,
                 steps_per_dispatch: int = 1,
                 quantize: Optional[str] = None,
                 allocation: str = "incremental",
                 speculative: Optional[int] = None,
                 spec_max_ngram: int = 3,
                 spec_sampled: bool = False,
                 spec_draft_layers: Optional[int] = None,
                 prefix_cache: str = "registered"):
        if not getattr(net, "_initialized", False):
            net.init()
        self.net = net
        self.n_slots = int(n_slots)
        self.steps_per_dispatch = int(steps_per_dispatch)
        if self.steps_per_dispatch < 1:
            raise ValueError(
                f"steps_per_dispatch must be >= 1; got {steps_per_dispatch}")
        self.top_k = None if top_k is None else int(top_k)
        if self.n_slots < 1:
            raise ValueError(f"n_slots must be >= 1; got {n_slots}")
        if allocation not in ("incremental", "upfront"):
            raise ValueError(
                f"allocation must be 'incremental' or 'upfront'; "
                f"got {allocation!r}")
        self.allocation = allocation
        self.quantize = quantize
        if speculative is not None:
            speculative = int(speculative)
            if speculative < 2:
                raise ValueError(
                    f"speculative (the draft depth k) must be >= 2 — "
                    f"k=1 is ordinary decode; got {speculative}")
        self.spec_k = speculative
        self.spec_max_ngram = int(spec_max_ngram)
        # sampled speculation (rejection sampling over delta drafts —
        # zoo.transformer.rejection_sample_drafts): OPT-IN because it
        # trades the sampled bit-parity contract for a distributional
        # one (docs/SERVING.md acceptance-oracle table); greedy slots
        # keep the bit-exact argmax oracle either way
        self.spec_sampled = bool(spec_sampled)
        if self.spec_sampled and self.spec_k is None:
            raise ValueError(
                "spec_sampled=True without speculative=k — there is "
                "no draft depth to rejection-sample over")
        # truncated-layer drafter: the SECOND _propose backend — the
        # first `spec_draft_layers` transformer blocks of the SAME
        # weights greedily draft k-1 tokens when the n-gram suffix
        # cache has nothing (non-repetitive text)
        if spec_draft_layers is not None:
            spec_draft_layers = int(spec_draft_layers)
            if self.spec_k is None:
                raise ValueError(
                    "spec_draft_layers without speculative=k — the "
                    "drafter only feeds speculative dispatches")
        self.spec_draft_layers = spec_draft_layers
        if prefix_cache not in ("registered", "radix"):
            raise ValueError(
                f"prefix_cache must be 'registered' or 'radix'; "
                f"got {prefix_cache!r}")
        self.prefix_cache_mode = prefix_cache
        # pay the quantization pass NOW, not inside the first live
        # dispatch (the tree itself is resolved per dispatch — see
        # the _params property)
        quant.serving_params(net, quantize)
        budget = stream_budget(net.layers)
        if budget is None:
            raise ValueError(
                "net has no bounded stream budget (no TransformerEncoder"
                "Block / PositionalEncodingLayer) — nothing to page")
        if budget % block_len != 0:
            raise ValueError(
                f"block_len {block_len} must divide the stream budget "
                f"{budget} (KV cache_len / positional max_len): the "
                f"gathered page view must have the same length as the "
                f"monolithic cache for decode parity")
        vocab = getattr(net.layers[-1], "n_out", None)
        if self.top_k is not None and not (1 <= self.top_k <=
                                           (vocab or self.top_k)):
            raise ValueError(f"top_k must be in [1, vocab={vocab}]; "
                             f"got {top_k}")
        self.max_blocks = budget // int(block_len)
        self.max_total_tokens = budget
        if self.spec_k is not None and self.spec_k > budget:
            raise ValueError(
                f"speculative depth {self.spec_k} exceeds the stream "
                f"budget {budget} — no slot could ever take a full-"
                f"depth dispatch")
        self.pool = PagedKVPool(net, n_blocks, block_len)
        self.block_len = int(block_len)
        # a serving "plan": how each layer participates in the paged
        # decode walk. Input preprocessors would silently change the
        # math mid-walk — reject loudly (the zoo LMs have none).
        if net.conf.input_preprocessors:
            raise ValueError(
                "paged decode does not support input preprocessors "
                f"(found at {sorted(net.conf.input_preprocessors)})")
        self._plan: List[Tuple] = []
        pool_j = 0
        for i, layer in enumerate(net.layers):
            if isinstance(layer, TransformerEncoderBlock):
                self._plan.append(("block", i, pool_j))
                pool_j += 1
            elif isinstance(layer, PositionalEncodingLayer):
                self._plan.append(("pos", i))
            elif isinstance(layer, BaseRecurrentLayer):
                raise ValueError(
                    f"layer {i} ({type(layer).__name__}) carries "
                    "recurrent state but has no paged decode path")
            else:
                self._plan.append(("plain", i))
        # truncated-drafter plan: the SAME walk minus the deep blocks —
        # embedding/positional/unembedding layers all kept, only the
        # first `spec_draft_layers` ("block", i, j) entries survive.
        # Layer-i K/V depends only on layers < i, so the slot's real
        # pages double as the draft model's cache for committed tokens
        # with NO extra state
        self._draft_plan: Optional[List[Tuple]] = None
        if self.spec_draft_layers is not None:
            n_layers = sum(1 for e in self._plan if e[0] == "block")
            if not (1 <= self.spec_draft_layers < n_layers):
                raise ValueError(
                    f"spec_draft_layers must be in [1, {n_layers - 1}] "
                    f"(a strict truncation of the {n_layers}-block "
                    f"target); got {self.spec_draft_layers}")
            kept = 0
            self._draft_plan = []
            for e in self._plan:
                if e[0] == "block":
                    if kept >= self.spec_draft_layers:
                        continue
                    kept += 1
                self._draft_plan.append(e)
        # host slot state (uploaded per step; a few [S] vectors)
        S = self.n_slots
        self.block_tables = np.zeros((S, self.max_blocks), np.int32)
        self.pos = np.zeros(S, np.int32)
        self.active = np.zeros(S, bool)
        self.remaining = np.zeros(S, np.int32)
        self.emit_idx = np.zeros(S, np.int32)
        self.last_token = np.zeros(S, np.int32)
        self.keys = np.zeros((S, 2), np.uint32)
        self.temp = np.zeros(S, np.float32)
        self.top_p = np.ones(S, np.float32)
        self.slots: List[Optional[Slot]] = [None] * S
        self._decode_full = None      # greedy + sampling chain
        self._decode_greedy = None    # argmax only (no sort/rng ops)
        self._admit_finish = {}       # k -> fused write-pages+first-token
        # K-position score programs (speculative decode + CoW suffix
        # extension), keyed (K, greedy_only — K is baked into the
        # array shapes, but the variants differ in OPS); the fork copy
        # and the first-token samplers (exact prefix-match admission,
        # keyed greedy_only) are shape-polymorphic single jits — jit's
        # own per-shape cache covers every pow2 width
        self._score = {}
        self._fork = None
        self._first_token = {}
        self._draft_fn = None         # truncated-layer draft scan
        # copy-on-write shared-prefix registry: key (token-id tuple) ->
        # {tokens, len, blocks, probs}; the cache itself holds one
        # allocator reference per block so registered prefixes survive
        # every slot release
        self._prefixes: Dict[tuple, dict] = {}
        self.prefix_pinned_blocks = 0
        # radix prefix cache (prefix_cache="radix"): automatic
        # block-aligned mid-prompt dedup across all admissions — the
        # registered-prefix registry above keeps working alongside it
        # (exact registered matches win; the tree catches everything
        # else). Radix-held blocks are NOT pinned capacity: eviction
        # reclaims them on demand (LRU leaves first, live slots never)
        self._radix: Optional[RadixPrefixCache] = (
            RadixPrefixCache(self.pool.allocator, self.block_len)
            if prefix_cache == "radix" else None)
        self.radix_hit_tokens_total = 0
        self.radix_evictions_total = 0
        # allocator observability (host ints — the scheduler mirrors
        # them onto the metrics registry) + preemption notices the
        # scheduler drains for requeue
        self.block_grants_total = 0
        self.evict_requeue_total = 0
        # speculative-decoding accounting (host ints; the scheduler's
        # accept-rate EWMA and the serving_spec_* gauges read them)
        self.spec_dispatches_total = 0
        self.spec_proposed_total = 0
        self.spec_accepted_total = 0
        self.spec_emitted_total = 0
        # per-proposer split of the same accounting (the scheduler's
        # per-proposer EWMAs and the serving_spec_*{proposer=} label
        # families read these; the global counters above are the sum
        # over proposers and keep their exact PR-14 semantics)
        self.spec_proposed_by: Dict[str, int] = {"ngram": 0,
                                                 "truncated": 0}
        self.spec_accepted_by: Dict[str, int] = {"ngram": 0,
                                                 "truncated": 0}
        self.spec_draft_dispatches_total = 0
        # shared-prefix accounting
        self.prefix_hits_total = 0
        self.prefix_tokens_saved_total = 0
        self.prefix_forks_total = 0
        # token-goodput ledger: every dispatch site classifies the
        # token-positions of the program it launches (host ints; the
        # scheduler mirrors the classes onto the registry) — sum of
        # classes == dispatched_total by construction
        from deeplearning4j_tpu.monitor.goodput import GoodputLedger
        self.goodput = GoodputLedger()
        self._preempted: List[dict] = []
        # per-slot attribution for the LAST admit_many wave (host-side
        # bookkeeping only — what request tracing reads to say whether
        # an admission rode a shared prefix / forked CoW blocks)
        self.admit_info: dict = {}

    # ------------------------------------------------------------ queries
    @property
    def _params(self):
        """The params tree every serving program reads: int8-quantized
        matmul weights under quantize="int8" (nd/quant.py), the net's
        own tree otherwise — resolved PER DISPATCH, so a fit()/restore
        between dispatches serves the fresh weights (serving_params'
        identity-keyed cache makes this a dict lookup; quantization
        re-runs only when net.params was reassigned)."""
        return quant.serving_params(self.net, self.quantize)

    @property
    def free_slots(self) -> int:
        return sum(1 for s in self.slots if s is None)

    @property
    def active_slots(self) -> int:
        return int(self.active.sum())

    @property
    def free_blocks(self) -> int:
        return self.pool.free_blocks

    def _admit_blocks(self, prompt_len: int, n_tokens: int) -> int:
        """Blocks an admission grants NOW: the prompt's footprint under
        incremental allocation (decode growth is lazy), the request's
        whole budget under the PR-9 upfront policy."""
        if self.allocation == "incremental":
            return blocks_needed(prompt_len, self.block_len)
        return blocks_needed(prompt_len + n_tokens, self.block_len)

    @property
    def has_prefixes(self) -> bool:
        return bool(self._prefixes)

    def _match_prefix(self, prompt) -> Optional[dict]:
        """The LONGEST registered prefix that prefixes `prompt`, or
        None. O(#prefixes x prefix_len) numpy compares — the registry
        holds a handful of warmed system prompts, not a trie."""
        if not self._prefixes:
            return None
        best = None
        # list() snapshot: submitter threads run this through
        # check_budget while the scheduler thread applies register/
        # release control requests — iterating the live dict would
        # raise "changed size during iteration" in an innocent submit
        for e in list(self._prefixes.values()):
            P = e["len"]
            if P > prompt.shape[0]:
                continue
            if best is not None and P <= best["len"]:
                continue
            if np.array_equal(np.asarray(prompt[:P], np.int64),
                              e["tokens"]):
                best = e
        return best

    def _cow_fresh_blocks(self, entry: dict, map_tokens: int) -> int:
        """Fresh (non-shared) blocks a CoW admission mapping
        `map_tokens` positions must allocate: the full map minus the
        shared prefix blocks, plus one for the forked tail when the
        prefix ends mid-block (copy-on-first-write — the fork target
        is a fresh block; the slot's reference on the shared source is
        dropped at fork time)."""
        nb_sh = blocks_needed(entry["len"], self.block_len)
        fork = 0 if entry["len"] % self.block_len == 0 else 1
        return blocks_needed(map_tokens, self.block_len) - nb_sh + fork

    def _reclaimable_blocks(self) -> int:
        """Blocks an admission could obtain right now: the free list
        plus whatever evicting the whole unpinned radix tree would
        return (cache-only references — `_alloc_admit` realizes them
        LRU-first on demand)."""
        extra = (self._radix.evictable_blocks
                 if self._radix is not None else 0)
        return self.pool.free_blocks + extra

    def _match_radix(self, prompt) -> Optional[dict]:
        """Longest block-aligned radix-cached prefix of `prompt` as a
        synthetic CoW entry (the same dict shape `_match_prefix`
        returns, minus cached probs — a radix match is always capped
        BELOW the full prompt, so the suffix-extension score path
        computes the first token and no cached distribution is ever
        needed; block alignment means the mid-block fork never
        fires)."""
        if self._radix is None:
            return None
        P = int(prompt.shape[0])
        matched, blocks = self._radix.match(prompt)
        if matched >= P:
            matched -= self.block_len
            blocks = blocks[:-1]
        if matched <= 0:
            return None
        return dict(tokens=np.asarray(prompt[:matched], np.int64),
                    len=matched, blocks=blocks, probs=None, radix=True)

    def _alloc_admit(self, n: int) -> Optional[List[int]]:
        """Admission-path allocation: on pool exhaustion, evict radix
        LRU leaves (cache-only references — never a live slot) until
        the grant fits or nothing evictable remains."""
        got = self.pool.allocator.allocate(n)
        while got is None and self._radix is not None:
            if not self._radix.evict_lru():
                break
            self.radix_evictions_total += 1
            got = self.pool.allocator.allocate(n)
        return got

    def can_admit(self, prompt_len: int, n_tokens: int,
                  prompt_ids=None) -> bool:
        if not any(s is None for s in self.slots):
            return False
        if prompt_ids is not None and (self._prefixes
                                       or self._radix is not None):
            prompt = np.asarray(prompt_ids)
            entry = self._match_prefix(prompt)
            if entry is None:
                entry = self._match_radix(prompt)
            if entry is not None:
                map_tokens = (prompt_len if self.allocation == "incremental"
                              else prompt_len + n_tokens)
                return (self._cow_fresh_blocks(entry, map_tokens)
                        <= self._reclaimable_blocks())
        return self._admit_blocks(prompt_len, n_tokens) \
            <= self._reclaimable_blocks()

    def check_budget(self, prompt_len: int, n_tokens: int,
                     prompt_ids=None):
        """Reject requests that can NEVER be admitted — distinct from
        `can_admit` (not right now): over the per-sequence page budget,
        or needing more blocks AT THE END than the pool can ever free
        up (under incremental allocation a request must still be able
        to finish alone in the pool — pool-pressure preemption can
        evict every OTHER slot, never conjure capacity, and blocks
        pinned by the shared-prefix cache never free). With
        `prompt_ids`, a request that RIDES a registered prefix is
        charged only its fresh blocks — sharing is exactly what makes
        an otherwise-oversized request admittable."""
        total = prompt_len + n_tokens
        if n_tokens < 1:
            raise ValueError(f"n_tokens must be >= 1; got {n_tokens}")
        if total > self.max_total_tokens:
            raise ValueError(
                f"prompt ({prompt_len}) + n_tokens ({n_tokens}) = {total} "
                f"exceeds the per-sequence page budget "
                f"{self.max_total_tokens} (max_blocks {self.max_blocks} x "
                f"block_len {self.block_len}); this request can never be "
                f"admitted — rebuild the model with a larger max_len")
        # id 0 is the garbage block; prefix-cache pins never free
        usable = self.pool.n_blocks - 1 - self.prefix_pinned_blocks
        needed = blocks_needed(total, self.block_len)
        if prompt_ids is not None and self._prefixes:
            entry = self._match_prefix(np.asarray(prompt_ids))
            if entry is not None:
                needed = self._cow_fresh_blocks(entry, total)
        if needed > usable:
            raise ValueError(
                f"request needs {needed} "
                f"pool blocks but the pool only has {usable} usable "
                f"(n_blocks {self.pool.n_blocks} incl. the reserved "
                f"garbage block and {self.prefix_pinned_blocks} pinned "
                f"by registered prefixes); it can never be admitted — "
                f"grow n_blocks or shorten the request")

    # ----------------------------------------------------------- sampling
    def _sample_ids(self, probs, keys, emit_idx, temp, top_p,
                    greedy_only: bool = False):
        """Next token per row of `probs` [S, V]: greedy argmax where
        temp == 0 (bit-identical to `generate(temperature=0)`), else
        the same log/clip/filter/categorical chain `generate` runs —
        with a PER-SLOT key folded by emit index, the serving rng
        contract. `greedy_only=True` (a STATIC program variant the
        scheduler picks when no sampled request is in flight) skips
        the sort/threefry chain entirely — measured at ~half the
        decode chunk on the CPU sandbox."""
        greedy_ids = jnp.argmax(probs, axis=-1).astype(jnp.int32)
        if greedy_only:
            return greedy_ids
        from deeplearning4j_tpu.zoo.transformer import filter_logits
        safe_t = jnp.where(temp > 0, temp, 1.0)
        logits = jnp.log(jnp.clip(probs, 1e-9, None)) / safe_t[:, None]
        # generate()'s own filter body, with per-slot traced p
        # (p=1.0 keeps everything)
        logits = filter_logits(logits, self.top_k, top_p[:, None])
        skeys = jax.vmap(jax.random.fold_in)(keys, emit_idx)
        sampled = jax.vmap(jax.random.categorical)(skeys, logits)
        return jnp.where(temp > 0, sampled.astype(jnp.int32), greedy_ids)

    # ------------------------------------------------------ jit builders
    def _shared_jit(self, key, builder):
        """Jitted-program cache shared ACROSS engines of the same net —
        anchored on `net.__dict__` (the `get_prefill_bucketed` idiom).
        A per-engine `jax.jit(closure)` is a fresh callable every
        construction, so every hot-swap successor and every tenant of a
        shared base used to pay the full ~10s+ decode/admit compile
        again; a `tenancy._TenantNetView` pre-seeds this attribute with
        the base net's dict, so N tenant servers and every adapter
        swap reuse ONE compile (params are arguments, never baked in).
        Keys carry every non-shape static the closure bakes into the
        trace (plan, scan length, greedy variant, top_k, block_len) —
        shape specialization is jit's own per-shape cache."""
        cache = self.net.__dict__.setdefault("_serving_jit_cache", {})
        fn = cache.get(key)
        if fn is None:
            fn = cache[key] = builder()
        return fn

    def _decode_body(self, greedy_only: bool):
        """The decode-chunk python body (jitted by `_build_decode`;
        traced directly by `decode_cost_report` for the byte-table
        evidence)."""
        net, layers, plan = self.net, self.net.layers, self._plan
        J = self.steps_per_dispatch

        def one_token(params, state, kv, block_tables, token_ids, pos,
                      keys, emit_idx, temp, top_p):
            h = token_ids[:, None]            # [S, 1] int ids
            kv = list(kv)
            for entry in plan:
                kind, i = entry[0], entry[1]
                layer = layers[i]
                lp = params.get(str(i), {})
                ls = state.get(str(i), {})
                if kind == "plain":
                    h, _ = layer.forward(lp, ls, h, train=False, rng=None)
                elif kind == "pos":
                    h, _ = layer.forward_at_positions(lp, ls, h, pos)
                else:
                    j = entry[2]
                    k_pool, v_pool = kv[j]
                    h, k_pool, v_pool = layer.forward_paged(
                        lp, h, k_pool, v_pool, block_tables, pos)
                    kv[j] = (k_pool, v_pool)
            probs = h[:, -1]                   # [S, V]
            return tuple(kv), self._sample_ids(probs, keys, emit_idx,
                                               temp, top_p,
                                               greedy_only=greedy_only)

        def decode_step(params, state, kv, block_tables, token_ids,
                        pos, remaining, keys, emit_idx, temp, top_p):
            """`steps_per_dispatch` micro-steps fused into ONE program
            via lax.scan: host round-trip and dispatch overhead
            amortize over J tokens x S slots (the continuous-batching
            counterpart of `generate()`'s fused decode scan). A slot
            finishing mid-chunk keeps decoding — into its own pages or
            the garbage block, never another slot's — and the `valids`
            mask tells the host which emissions are real. J=1 is the
            admit-every-token schedule the scheduler defaults to."""
            params = net.dtype.cast_params(params)

            def micro(carry, _):
                kv, tok, pos, rem, emit = carry
                kv, nxt = one_token(params, state, kv, block_tables,
                                    tok, pos, keys, emit, temp, top_p)
                return ((kv, nxt, pos + 1, rem - 1, emit + 1),
                        (nxt, rem > 0))

            carry = (kv, token_ids, pos, remaining, emit_idx)
            (kv, _, _, _, _), (toks, valids) = jax.lax.scan(
                micro, carry, None, length=J)
            return kv, toks, valids            # [J, S] each

        return decode_step

    def _build_decode(self, greedy_only: bool):
        return self._shared_jit(
            ("decode", greedy_only, self.steps_per_dispatch,
             tuple(self._plan), self.top_k),
            lambda: jax.jit(self._decode_body(greedy_only),
                            donate_argnums=donate_argnums(2)))

    def decode_cost_report(self) -> dict:
        """Byte accounting of the REAL decode program (greedy variant)
        via the hlo_cost per-op tables — the quantization ledger's
        evidence seam: weight HBM bytes of the params tree the program
        reads, split matmul-weights vs total, plus the per-op
        operand+result byte totals of one traced decode chunk."""
        from benchtools import hlo_cost

        S = self.n_slots
        args = (self._params, self.net.net_state, self.pool.kv,
                jnp.asarray(self.block_tables), jnp.asarray(self.last_token),
                jnp.asarray(self.pos), jnp.asarray(self.remaining),
                jnp.asarray(self.keys), jnp.asarray(self.emit_idx),
                jnp.asarray(self.temp), jnp.asarray(self.top_p))
        jaxpr = jax.make_jaxpr(self._decode_body(greedy_only=True))(*args)
        table = hlo_cost.per_op_table(jaxpr,
                                      fused_steps=self.steps_per_dispatch)
        mm_keys = quant.quantized_weight_keys(self.net)
        mm_bytes = quant.weight_bytes(
            {lk: {pk: self._params[lk][pk] for pk in pks}
             for lk, pks in mm_keys.items()})
        return {
            "quantize": self.quantize,
            "weight_bytes": quant.weight_bytes(self._params),
            "matmul_weight_bytes": mm_bytes,
            "decode_bytes_per_step": table["total_bytes_per_step"],
            "decode_flops_per_step": table["total_flops_per_step"],
            "n_slots": S,
        }

    def _build_admit_finish(self, k: int, greedy_only: bool):
        """One fused dispatch completing a k-wide admission wave:
        scatter every sequence's monolithic prefill K/V into its pool
        pages AND sample the wave's first tokens from the prefill
        probs. Separate per-request dispatches here were measured to
        cost as much as a whole `generate()` call each on the CPU
        sandbox — admission overhead is exactly what the sequential
        baseline pays, so it must be amortized for continuous batching
        to win."""
        bl = self.block_len

        def admit_finish(kv, rows, block_carries, probs, keys, emit0,
                         temp, top_p):
            # rows [k, max_rows]; block_carries: per layer (k_cache,
            # v_cache) with leading dim k; probs [k, V]; emit0 [k] is
            # the sampled-rng emit offset (nonzero for a requeued
            # continuation — its stream keeps the fold_in(key, t)
            # indices it would have had uninterrupted)
            out = []
            for (k_pool, v_pool), (k_cache, v_cache) in zip(
                    kv, block_carries):
                C = k_cache.shape[1]
                shape = (k * (C // bl), bl) + k_cache.shape[2:]
                flat_rows = rows[:, :C // bl].reshape(-1)
                out.append((
                    k_pool.at[flat_rows].set(
                        k_cache.reshape(shape).astype(k_pool.dtype)),
                    v_pool.at[flat_rows].set(
                        v_cache.reshape(shape).astype(v_pool.dtype)),
                ))
            firsts = self._sample_ids(probs, keys, emit0, temp, top_p,
                                      greedy_only=greedy_only)
            return tuple(out), firsts

        return self._shared_jit(
            ("admit", int(k), greedy_only, self.block_len, self.top_k),
            lambda: jax.jit(admit_finish,
                            donate_argnums=donate_argnums(0)))

    def _score_body(self, greedy_only: bool):
        """The K-position score program (zoo.transformer.
        paged_score_forward): ONE target-model dispatch scores K
        proposed tokens per slot — speculative decoding's target half
        — or extends a shared prefix by a K-bucketed suffix (CoW
        admission). Returns (kv', greedy_mat [S, K] — the target's
        argmax after each position, the acceptance oracle — and
        chosen [S], the sampled/greedy token at each slot's LAST valid
        position, which is the first emitted token on the suffix
        path and the sampled-slot token on the speculative path)."""
        net, plan = self.net, self._plan
        from deeplearning4j_tpu.zoo.transformer import paged_score_forward

        def score(params, state, kv, block_tables, token_mat, pos,
                  n_valid, keys, emit_idx, temp, top_p):
            params = net.dtype.cast_params(params)
            kv, probs = paged_score_forward(
                net, plan, params, state, kv, block_tables, token_mat,
                pos, n_valid)
            greedy_mat = jnp.argmax(probs, axis=-1).astype(jnp.int32)
            last = jnp.take_along_axis(
                probs, jnp.maximum(n_valid - 1, 0)[:, None, None],
                axis=1)[:, 0]                              # [S, V]
            chosen = self._sample_ids(last, keys, emit_idx, temp, top_p,
                                      greedy_only=greedy_only)
            return kv, greedy_mat, chosen

        return score

    def _score_rs_body(self):
        """The sampled-speculation score variant (`spec_sampled=True`
        dispatches with sampled slots in flight): same target forward,
        but the sampling tail is the rejection-sampling chain
        (zoo.transformer.rejection_sample_drafts) — per slot it
        returns how many leading drafts survived (`n_acc`) and the
        residual/bonus token at the first divergence (`final`).
        Greedy slots in the same dispatch keep the bit-exact argmax
        oracle: the host reads their rows from `greedy_mat` and
        ignores the sampled outputs."""
        net, plan = self.net, self._plan
        from deeplearning4j_tpu.zoo.transformer import (
            paged_score_forward, rejection_sample_drafts)

        def score(params, state, kv, block_tables, token_mat, pos,
                  n_valid, keys, emit_idx, temp, top_p):
            params = net.dtype.cast_params(params)
            kv, probs = paged_score_forward(
                net, plan, params, state, kv, block_tables, token_mat,
                pos, n_valid)
            greedy_mat = jnp.argmax(probs, axis=-1).astype(jnp.int32)
            n_acc, final = rejection_sample_drafts(
                probs, token_mat, n_valid, keys, emit_idx, temp,
                top_p, self.top_k)
            return kv, greedy_mat, n_acc, final

        return score

    def _get_score(self, K: int, variant):
        """`variant`: True/False = the greedy_only split, "rs" = the
        rejection-sampling tail (sampled speculation)."""
        key = (int(K), variant)
        fn = self._score.get(key)
        if fn is None:
            def build():
                body = (self._score_rs_body() if variant == "rs"
                        else self._score_body(variant))
                return jax.jit(body, donate_argnums=donate_argnums(2))
            fn = self._score[key] = self._shared_jit(
                ("score", int(K), variant, tuple(self._plan),
                 self.top_k), build)
        return fn

    def _build_fork(self):
        """Copy-on-write block fork: one dispatch copies a vector of
        pool blocks src -> dst across every layer's K and V pool.
        Unused lanes point both ids at the garbage block (a garbage-
        to-garbage self-copy — the one block whose content is never
        read). One jit; each pow2 pair-vector width is its own
        shape-keyed executable."""

        def fork(kv, src, dst):
            out = []
            for k_pool, v_pool in kv:
                out.append((k_pool.at[dst].set(k_pool[src]),
                            v_pool.at[dst].set(v_pool[src])))
            return tuple(out)

        return self._shared_jit(
            ("fork",),
            lambda: jax.jit(fork, donate_argnums=donate_argnums(0)))

    def _run_fork(self, pairs):
        w = 1
        while w < len(pairs):
            w *= 2
        src = np.full(w, GARBAGE_BLOCK, np.int32)
        dst = np.full(w, GARBAGE_BLOCK, np.int32)
        for i, (s, d) in enumerate(pairs):
            src[i], dst[i] = s, d
        if self._fork is None:
            self._fork = self._build_fork()
        self.pool.kv = self._fork(self.pool.kv, jnp.asarray(src),
                                  jnp.asarray(dst))
        self.prefix_forks_total += len(pairs)

    def _build_first_token(self, greedy_only: bool):
        """Sampling tail alone (no forward): first tokens of exact-
        prefix-match admissions, whose next-token distribution was
        cached at registration. The same `_sample_ids` chain the
        admit/decode programs run — same math, same bits."""

        def first(probs, keys, emit0, temp, top_p):
            return self._sample_ids(probs, keys, emit0, temp, top_p,
                                    greedy_only=greedy_only)

        return self._shared_jit(("first", greedy_only, self.top_k),
                                lambda: jax.jit(first))

    def _draft_body(self):
        """The truncated-layer draft scan: k-1 greedy micro-steps of
        the FIRST `spec_draft_layers` transformer blocks (same
        weights, same embedding/positional/unembedding — the plan
        minus its deep blocks) fused into one program. The slot's real
        pages are the draft model's KV cache for free: layer-i K/V
        depends only on layers < i, so the full model's committed
        pages ARE the truncated model's. Draft K/V writes land in the
        slot's not-yet-committed write window — every one of those
        positions is rewritten with full-model K/V by the verify
        dispatch in the same `_spec_step` (write-before-read, the same
        discipline rejected speculative lanes ride). Non-drafting
        slots' table rows point at the garbage block."""
        net, layers = self.net, self.net.layers
        dplan = self._draft_plan

        def draft(params, state, kv, block_tables, token_ids, pos):
            params = net.dtype.cast_params(params)

            def micro(carry, _):
                kv, tok, pos = carry
                h = tok[:, None]            # [S, 1] int ids
                kv = list(kv)
                for entry in dplan:
                    kind, i = entry[0], entry[1]
                    layer = layers[i]
                    lp = params.get(str(i), {})
                    ls = state.get(str(i), {})
                    if kind == "plain":
                        h, _ = layer.forward(lp, ls, h, train=False,
                                             rng=None)
                    elif kind == "pos":
                        h, _ = layer.forward_at_positions(lp, ls, h, pos)
                    else:
                        j = entry[2]
                        k_pool, v_pool = kv[j]
                        h, k_pool, v_pool = layer.forward_paged(
                            lp, h, k_pool, v_pool, block_tables, pos)
                        kv[j] = (k_pool, v_pool)
                nxt = jnp.argmax(h[:, -1], axis=-1).astype(jnp.int32)
                return (tuple(kv), nxt, pos + 1), nxt

            carry = (kv, token_ids, pos)
            (kv, _, _), drafts = jax.lax.scan(micro, carry, None,
                                              length=self.spec_k - 1)
            return kv, drafts               # [k-1, S]

        return draft

    def _run_draft(self, trunc_slots):
        """One truncated-layer draft dispatch over `trunc_slots`
        ([(slot, depth)] — write windows already granted/forked).
        Returns the [k-1, S] draft matrix; rows of non-participating
        slots are garbage and never read. Ledger: draft positions
        never emit directly (the verify dispatch emits), so the real
        lanes are speculation overhead — spec_rejected — and the
        masked lanes padding."""
        S, K = self.n_slots, self.spec_k
        mask = np.zeros(S, bool)
        for s, _ in trunc_slots:
            mask[s] = True
        tables = np.where(mask[:, None], self.block_tables,
                          GARBAGE_BLOCK).astype(np.int32)
        if self._draft_fn is None:
            self._draft_fn = self._shared_jit(
                ("draft", self.spec_k, tuple(self._draft_plan or ())),
                lambda: jax.jit(self._draft_body(),
                                donate_argnums=donate_argnums(2)))
        kv, drafts = self._draft_fn(
            self._params, self.net.net_state, self.pool.kv,
            jnp.asarray(tables), jnp.asarray(self.last_token),
            jnp.asarray(self.pos))
        self.pool.kv = kv
        self.spec_draft_dispatches_total += 1
        real = sum(d - 1 for _, d in trunc_slots)
        self.goodput.account(spec_rejected=real,
                             pad_waste=(K - 1) * S - real)
        return np.asarray(drafts)

    # ------------------------------------------------- shared prefixes
    def register_prefix(self, token_ids) -> tuple:
        """Warm a shared prompt prefix into the pool ONCE: prefill it
        (the same bucketed-prefill program family admission waves
        run), scatter its K/V into dedicated pool blocks, and pin
        those blocks under a cache-held allocator reference. Every
        later admission whose prompt starts with these ids maps the
        blocks instead of re-prefilling them (`serving_prefix_hits_
        total` / `serving_prefix_blocks_shared`). Idempotent per id
        sequence; returns the registry key. Raises when the pool
        cannot host the prefix right now — registration is a capacity
        commitment, not a best-effort hint."""
        prompt = np.asarray(token_ids)
        if prompt.ndim == 2 and prompt.shape[0] == 1:
            prompt = prompt[0]
        if prompt.ndim != 1 or prompt.size == 0:
            raise ValueError(
                f"prefix must be a non-empty 1-D id sequence; got "
                f"shape {prompt.shape}")
        P = int(prompt.shape[0])
        if P >= self.max_total_tokens:
            raise ValueError(
                f"prefix of {P} tokens leaves no room to generate "
                f"under the {self.max_total_tokens}-token page budget")
        key = tuple(int(t) for t in prompt)
        if key in self._prefixes:
            return key
        nb = blocks_needed(P, self.block_len)
        blocks = self.pool.allocator.allocate(nb)
        if blocks is None:
            raise ValueError(
                f"pool cannot host a {nb}-block prefix right now "
                f"({self.pool.free_blocks} free) — register prefixes "
                f"before admitting traffic, or grow n_blocks")
        try:
            from deeplearning4j_tpu.zoo.transformer import (
                get_prefill_bucketed)
            net = self.net
            Pb = bucket_len(P, self.max_total_tokens)
            prompts = np.zeros((1, Pb), np.int32)
            prompts[0, :P] = prompt
            carries = {str(i): layer.init_carry(1, net.dtype.compute_dtype)
                       for i, layer in enumerate(net.layers)
                       if isinstance(layer, BaseRecurrentLayer)}
            probs, carries = get_prefill_bucketed(net)(
                self._params, net.net_state, jnp.asarray(prompts),
                carries, jnp.asarray([P - 1], np.int32))
            block_carries = [carries[str(i)]
                             for i in self.pool.layer_indices]
            max_rows = max(c[0].shape[1] // self.block_len
                           for c in block_carries)
            rows = np.full((1, max_rows), GARBAGE_BLOCK, np.int32)
            rows[0, :nb] = blocks
            # the admit_finish program scatters the pages; its sampled
            # first token is discarded (registration emits nothing) —
            # but the LAST-position probs are kept: an exact-match
            # admission samples its first token from them with no
            # forward pass at all
            fin = self._admit_finish.get((1, True))
            if fin is None:
                fin = self._admit_finish[(1, True)] = \
                    self._build_admit_finish(1, True)
            self.pool.kv, _ = fin(
                self.pool.kv, jnp.asarray(rows),
                tuple((c[0], c[1]) for c in block_carries), probs,
                jnp.zeros((1, 2), np.uint32), jnp.zeros(1, np.int32),
                jnp.zeros(1, np.float32), jnp.ones(1, np.float32))
        except Exception:
            self.pool.allocator.free(blocks)
            raise
        self._prefixes[key] = dict(
            tokens=np.asarray(prompt, np.int64), len=P, blocks=blocks,
            probs=np.asarray(probs[0]))
        self.prefix_pinned_blocks += nb
        self.block_grants_total += nb
        # registration prefills once so later admissions don't: the P
        # real positions are useful, the bucket padding is waste
        self.goodput.account(useful=P, pad_waste=Pb - P)
        return key

    def release_prefix(self, key: tuple):
        """Unpin a registered prefix: the cache's block references
        drop; blocks still mapped by in-flight slots stay granted
        until those slots release (the refcount contract)."""
        entry = self._prefixes.pop(tuple(key))
        self.pool.allocator.free(entry["blocks"])
        self.prefix_pinned_blocks -= len(entry["blocks"])

    # ---------------------------------------------------------- admission
    def admit(self, prompt_ids, n_tokens: int, *, request_id=None,
              temperature: float = 0.0, top_p: Optional[float] = None,
              rng=None):
        """Single-request admission (a k=1 `admit_many` wave). Returns
        (slot index, first emitted token, done) or None when capacity
        can't take the request right now."""
        out = self.admit_many([dict(prompt_ids=prompt_ids,
                                    n_tokens=n_tokens,
                                    request_id=request_id,
                                    temperature=temperature,
                                    top_p=top_p, rng=rng)])
        return out[0] if out else None

    def admit_many(self, requests: List[dict]):
        """Admission wave: prefill up to len(requests) prompts — of
        HETEROGENEOUS lengths, right-padded to one power-of-two bucket
        — as one batch through the cached bucketed-prefill jit
        (zoo/transformer.get_prefill_bucketed: `generate()`'s forward
        with a per-slot last-position gather, so prefill numerics are
        its by construction), then one fused dispatch writes all their
        pool pages and samples all their first tokens. Requests beyond
        the wave's slot/block capacity are left unadmitted (the
        returned list is a PREFIX of the input — FIFO order
        preserved).

        Each request dict: prompt_ids, n_tokens, and optionally
        request_id, temperature, top_p, rng, emit_start (a requeued
        continuation's already-emitted token count — offsets the
        sampled-rng fold and the progress ordering). Returns
        [(slot, first_token, done), ...] for the admitted prefix."""
        if not requests:
            return []
        self.admit_info = {}
        wave = []
        try:
            for r in requests:
                prompt = np.asarray(r["prompt_ids"])
                if prompt.ndim == 2 and prompt.shape[0] == 1:
                    prompt = prompt[0]
                if prompt.ndim != 1 or prompt.size == 0:
                    raise ValueError(
                        f"prompt must be a non-empty 1-D id sequence; "
                        f"got shape {prompt.shape}")
                P = int(prompt.shape[0])
                n_tokens = int(r["n_tokens"])
                self.check_budget(P, n_tokens, prompt_ids=prompt)
                slot = next((i for i, s in enumerate(self.slots)
                             if s is None
                             and all(i != w["slot"] for w in wave)),
                            None)
                if slot is None:
                    break
                entry = self._match_prefix(prompt)
                if entry is None:
                    # no registered exact match — the radix tree
                    # catches block-aligned mid-prompt sharing across
                    # ALL prior admissions (prefix_cache="radix")
                    entry = self._match_radix(prompt)
                if entry is None:
                    nb = self._admit_blocks(P, n_tokens)
                    blocks = self._alloc_admit(nb)
                    if blocks is None:
                        break
                    w = dict(blocks=blocks, grants=nb, entry=None,
                             fork=None)
                else:
                    w = self._cow_admit_blocks(entry, P, n_tokens)
                    if w is None:
                        break
                w.update(slot=slot, prompt=prompt, n_tokens=n_tokens, r=r)
                wave.append(w)
            if not wave:
                return []
            out = self._admit_dispatch(wave)
            if self._radix is not None:
                # every admission's fully-written prompt blocks feed
                # the tree on the way in (automatic dedup — no manual
                # register/release); the partial tail block, which the
                # slot will keep writing, never enters
                for w in wave:
                    slot = self.slots[w["slot"]]
                    if slot is None:      # n_tokens == 1: already done
                        continue
                    n_full = len(w["prompt"]) // self.block_len
                    if n_full:
                        self._radix.insert(w["prompt"],
                                           slot.blocks[:n_full])
            return out
        except Exception:
            # a mid-wave failure (validation of a later request, a
            # prefill/admit dispatch error) must return the wave's
            # already-allocated blocks — no Slot owns them yet, so
            # _release could never recover them and the pool would
            # shrink permanently (capacity leak -> eventual silent
            # starvation of every later admission). Entries a Slot DID
            # take ownership of (partial bookkeeping) keep theirs —
            # the normal release path frees those. A CoW entry's list
            # mixes fresh blocks and shared-prefix references; `free`
            # handles both uniformly (fresh return to the free list,
            # shares decrement back to the cache's own reference).
            for w in wave:
                s = self.slots[w["slot"]]
                if s is None or s.blocks is not w["blocks"]:
                    try:
                        self.pool.allocator.free(w["blocks"])
                    except ValueError:
                        pass   # already back in the pool
            raise

    def _cow_admit_blocks(self, entry: dict, prompt_len: int,
                          n_tokens: int) -> Optional[dict]:
        """Block grants for a shared-prefix admission: take one
        allocator reference per shared prefix block, allocate the fresh
        remainder, and — when the prefix ends mid-block — fork the
        partially-filled tail NOW (copy-on-first-write realized at
        admission: the very next write, suffix prefill or first decode
        token, lands in that block, and a write into a block someone
        else still maps would corrupt every other reader). The fork
        drops this slot's just-taken reference on the shared source
        (the refcount-decrement half of the CoW contract); the cache's
        own reference keeps the source alive for the next admission.
        Returns the wave-entry dict, or None when the pool can't cover
        the fresh blocks right now."""
        alloc = self.pool.allocator
        bl = self.block_len
        P = entry["len"]
        nb_sh = blocks_needed(P, bl)
        map_tokens = (prompt_len if self.allocation == "incremental"
                      else prompt_len + n_tokens)
        n_fresh = self._cow_fresh_blocks(entry, map_tokens)
        # take the shared references BEFORE allocating fresh blocks:
        # covering the fresh grant may evict radix LRU nodes, and an
        # unshared match could be evicted out from under us — the
        # share pins the matched blocks regardless of what the tree
        # does
        alloc.share(entry["blocks"][:nb_sh])
        fresh = [] if n_fresh == 0 else self._alloc_admit(n_fresh)
        if fresh is None:
            alloc.free(entry["blocks"][:nb_sh])
            return None
        if P % bl == 0:
            blocks = list(entry["blocks"][:nb_sh]) + fresh
            fork = None
        else:
            src, dst = entry["blocks"][nb_sh - 1], fresh[0]
            alloc.free([src])                # drop OUR tail reference
            fork = (src, dst)
            blocks = list(entry["blocks"][:nb_sh - 1]) + [dst] + fresh[1:]
        return dict(blocks=blocks, grants=n_fresh, entry=entry, fork=fork)

    def _admit_dispatch(self, wave):
        """Route one capacity-granted admission wave through its
        dispatch paths — full prefill for fresh prompts, fork + suffix
        extension for shared-prefix hits — and return results in the
        wave's (FIFO) input order."""
        results = {}
        norm = [w for w in wave if w["entry"] is None]
        cow = [w for w in wave if w["entry"] is not None]
        if norm:
            self._admit_wave(norm, results)
        if cow:
            self._admit_wave_shared(cow, results)
        return [results[w["slot"]] for w in wave]

    def _admit_wave(self, wave, results):
        k = len(wave)
        # pad the wave WIDTH to the next power of two: every distinct
        # batch width costs a prefill + admit_finish COMPILE, and
        # free-slot counts vary chunk to chunk — unquantized widths
        # were measured as a compile storm that dwarfed the serving
        # itself. Dummy rows repeat the last prompt, scatter only into
        # the garbage block, and their sampled firsts are discarded.
        k2 = 1
        while k2 < k:
            k2 *= 2
        # pad the prompt LENGTHS to one power-of-two bucket (mixed-
        # length waves — the same-length restriction serialized
        # admissions under realistic traffic): right padding is sound
        # because the blocks are causal and the padding rows' K/V land
        # past each slot's position, where every later read masks them
        Pb = bucket_len(max(int(w["prompt"].shape[0]) for w in wave),
                        self.max_total_tokens)

        net = self.net
        from deeplearning4j_tpu.zoo.transformer import get_prefill_bucketed
        prefill = get_prefill_bucketed(net)
        carries = {str(i): layer.init_carry(k2, net.dtype.compute_dtype)
                   for i, layer in enumerate(net.layers)
                   if isinstance(layer, BaseRecurrentLayer)}
        prompts = np.zeros((k2, Pb), np.int32)
        last_idx = np.zeros(k2, np.int32)
        for j, w in enumerate(wave):
            prompts[j, :w["prompt"].shape[0]] = w["prompt"]
            last_idx[j] = w["prompt"].shape[0] - 1
        for j in range(k, k2):                # dummy width-padding rows
            prompts[j] = prompts[k - 1]
            last_idx[j] = last_idx[k - 1]
        probs, carries = prefill(self._params, net.net_state,
                                 jnp.asarray(prompts), carries,
                                 jnp.asarray(last_idx))

        block_carries = [carries[str(i)] for i in self.pool.layer_indices]
        max_rows = max(c[0].shape[1] // self.block_len
                       for c in block_carries)
        rows = np.full((k2, max_rows), GARBAGE_BLOCK, np.int32)
        keys = np.zeros((k2, 2), np.uint32)
        emit0 = np.zeros(k2, np.int32)
        temps = np.zeros(k2, np.float32)
        top_ps = np.ones(k2, np.float32)
        for j, w in enumerate(wave):
            rows[j, :len(w["blocks"])] = w["blocks"]
            r = w["r"]
            if r.get("rng") is not None:
                keys[j] = np.asarray(r["rng"], np.uint32).reshape(2)
            emit0[j] = int(r.get("emit_start") or 0)
            temps[j] = r.get("temperature") or 0.0
            p = r.get("top_p")
            top_ps[j] = 1.0 if p is None else p
        # all-greedy waves skip the sampling chain (sort + threefry) on
        # the TTFT-critical path — same static-variant split the
        # decode program uses
        greedy = not bool((temps > 0).any())
        fin = self._admit_finish.get((k2, greedy))
        if fin is None:
            fin = self._admit_finish[(k2, greedy)] = \
                self._build_admit_finish(k2, greedy)
        self.pool.kv, firsts = fin(
            self.pool.kv, jnp.asarray(rows),
            tuple((c[0], c[1]) for c in block_carries), probs,
            jnp.asarray(keys), jnp.asarray(emit0), jnp.asarray(temps),
            jnp.asarray(top_ps))
        firsts = np.asarray(firsts)

        # ledger: the prefill program touched k2*Pb token-positions —
        # live prompt positions are useful, a requeued continuation's
        # re-prefill is preempt_discard (that work was already done
        # once), width/length padding is pad_waste
        fresh = sum(int(w["prompt"].shape[0]) for w in wave
                    if not int(w["r"].get("emit_start") or 0))
        redone = sum(int(w["prompt"].shape[0]) for w in wave
                     if int(w["r"].get("emit_start") or 0))
        self.goodput.account(useful=fresh, preempt_discard=redone,
                             pad_waste=k2 * Pb - fresh - redone)

        for j, w in enumerate(wave):
            self._finish_admission(w, int(firsts[j]), keys[j], results)

    def _finish_admission(self, w, first, key, results):
        """Slot bookkeeping shared by the fresh-prefill and shared-
        prefix admission paths (one body — the two must not drift)."""
        slot, prompt, blocks = w["slot"], w["prompt"], w["blocks"]
        n_tokens, r = w["n_tokens"], w["r"]
        emit0 = int(r.get("emit_start") or 0)
        done = n_tokens == 1
        # token history feeds the self-drafting proposer only — a
        # non-speculative server skips the per-admission O(prompt)
        # copy and the per-dispatch extends entirely
        s = Slot(r.get("request_id"), blocks, len(prompt), n_tokens,
                 emit_base=emit0,
                 history=([int(t) for t in prompt] + [first]
                          if self.spec_k else []))
        s.emitted = 1
        self.slots[slot] = s
        self.block_tables[slot] = GARBAGE_BLOCK
        self.block_tables[slot, :len(blocks)] = blocks
        self.pos[slot] = len(prompt)
        self.remaining[slot] = n_tokens - 1
        self.emit_idx[slot] = emit0 + 1
        self.last_token[slot] = first
        self.keys[slot] = key
        self.temp[slot] = r.get("temperature") or 0.0
        p = r.get("top_p")
        self.top_p[slot] = 1.0 if p is None else p
        self.active[slot] = not done
        self.block_grants_total += w["grants"]
        self.admit_info[slot] = {
            "grants": int(w["grants"]),
            "prefix_hit": w["entry"] is not None,
            "tokens_saved": (int(w["entry"]["len"])
                             if w["entry"] is not None else 0),
            "cow_fork": w.get("fork") is not None,
        }
        if w["entry"] is not None:
            self.prefix_hits_total += 1
            self.prefix_tokens_saved_total += w["entry"]["len"]
            if w["entry"].get("radix"):
                self.radix_hit_tokens_total += w["entry"]["len"]
        if done:
            self._release(slot)
        results[slot] = (slot, first, done)

    def _admit_wave_shared(self, wave, results):
        """Shared-prefix (CoW) admission: the prefix blocks are already
        in the pool — fork any mid-block tails, run the K-position
        score program over the suffixes (ONE dispatch extends every
        hit past its shared region, attending the shared blocks
        through the slot's table), and sample first tokens — from the
        suffix scores, or from the prefix's cached last-position probs
        when the prompt IS the prefix. No monolithic prefill runs at
        all: that is the `serving_prefix_prefill_reduction` lever."""
        # fork copies must land BEFORE any suffix/decode write reaches
        # a block another holder still maps
        pairs = [w["fork"] for w in wave if w["fork"] is not None]
        if pairs:
            self._run_fork(pairs)
        for w in wave:
            slot = w["slot"]
            self.block_tables[slot] = GARBAGE_BLOCK
            self.block_tables[slot, :len(w["blocks"])] = w["blocks"]
            w["suffix"] = w["prompt"][w["entry"]["len"]:]
        keys_by_slot = {}
        firsts = {}
        ext = [w for w in wave if w["suffix"].shape[0] > 0]
        if ext:
            S = self.n_slots
            K = bucket_len(max(int(w["suffix"].shape[0]) for w in ext),
                           self.max_total_tokens)
            token_mat = np.zeros((S, K), np.int32)
            n_valid = np.zeros(S, np.int32)
            pos = np.zeros(S, np.int32)
            keys = np.zeros((S, 2), np.uint32)
            emit0 = np.zeros(S, np.int32)
            temps = np.zeros(S, np.float32)
            top_ps = np.ones(S, np.float32)
            for w in ext:
                s, r = w["slot"], w["r"]
                Ts = int(w["suffix"].shape[0])
                token_mat[s, :Ts] = w["suffix"]
                n_valid[s] = Ts
                pos[s] = w["entry"]["len"]
                if r.get("rng") is not None:
                    keys[s] = np.asarray(r["rng"], np.uint32).reshape(2)
                emit0[s] = int(r.get("emit_start") or 0)
                temps[s] = r.get("temperature") or 0.0
                p = r.get("top_p")
                top_ps[s] = 1.0 if p is None else p
                keys_by_slot[s] = keys[s].copy()
            greedy = not bool((temps > 0).any())
            score = self._get_score(K, greedy)
            kv, _, chosen = score(
                self._params, self.net.net_state, self.pool.kv,
                jnp.asarray(self.block_tables), jnp.asarray(token_mat),
                jnp.asarray(pos), jnp.asarray(n_valid),
                jnp.asarray(keys), jnp.asarray(emit0),
                jnp.asarray(temps), jnp.asarray(top_ps))
            self.pool.kv = kv
            chosen = np.asarray(chosen)
            for w in ext:
                firsts[w["slot"]] = int(chosen[w["slot"]])
            # ledger: the suffix-extension score program touched S*K
            # positions — live suffix positions are useful (the shared
            # prefix itself was accounted at registration), requeued
            # continuations are preempt_discard, the rest is padding
            fresh = sum(int(w["suffix"].shape[0]) for w in ext
                        if not int(w["r"].get("emit_start") or 0))
            redone = sum(int(w["suffix"].shape[0]) for w in ext
                         if int(w["r"].get("emit_start") or 0))
            self.goodput.account(useful=fresh, preempt_discard=redone,
                                 pad_waste=S * K - fresh - redone)
        # exact-match admissions (prompt == prefix): next-token probs
        # were computed ONCE at registration — nothing to prefill,
        # just run the sampling tail on the cached distribution
        empt = [w for w in wave if w["suffix"].shape[0] == 0]
        if empt:
            width = 1
            while width < len(empt):
                width *= 2
            probs0 = empt[0]["entry"]["probs"]
            probs = np.zeros((width,) + probs0.shape, probs0.dtype)
            keys = np.zeros((width, 2), np.uint32)
            emit0 = np.zeros(width, np.int32)
            temps = np.zeros(width, np.float32)
            top_ps = np.ones(width, np.float32)
            for j, w in enumerate(empt):
                r = w["r"]
                probs[j] = w["entry"]["probs"]
                if r.get("rng") is not None:
                    keys[j] = np.asarray(r["rng"], np.uint32).reshape(2)
                emit0[j] = int(r.get("emit_start") or 0)
                temps[j] = r.get("temperature") or 0.0
                p = r.get("top_p")
                top_ps[j] = 1.0 if p is None else p
                keys_by_slot[w["slot"]] = keys[j].copy()
            greedy = not bool((temps > 0).any())
            fn = self._first_token.get(greedy)
            if fn is None:
                fn = self._first_token[greedy] = \
                    self._build_first_token(greedy)
            ids = np.asarray(fn(jnp.asarray(probs), jnp.asarray(keys),
                                jnp.asarray(emit0), jnp.asarray(temps),
                                jnp.asarray(top_ps)))
            for j, w in enumerate(empt):
                firsts[w["slot"]] = int(ids[j])
        for w in wave:
            self._finish_admission(w, firsts[w["slot"]],
                                   keys_by_slot[w["slot"]], results)

    # -------------------------------------------- incremental block grants
    def _lowest_progress_active(self) -> int:
        """The pool-pressure eviction victim: the active slot whose
        REQUEST has emitted the fewest tokens (requeue costs it the
        least re-prefill work). Ties break toward the higher slot
        INDEX — an arbitrary but deterministic order (slot index is
        not admission order once retired slots are reused)."""
        best, best_p = -1, None
        for i in np.flatnonzero(self.active):
            i = int(i)
            p = self.slots[i].progress
            if best_p is None or p <= best_p:
                best, best_p = i, p
        return best

    def _preempt(self, slot: int):
        s = self.slots[slot]
        self._preempted.append({
            "slot": slot, "request_id": s.request_id,
            "emitted": s.progress,
        })
        self.evict_requeue_total += 1
        self._release(slot)

    def drain_preempted(self) -> List[dict]:
        """Preemption notices since the last drain: [{slot, request_id,
        emitted}] — the scheduler requeues each request as a
        continuation (prompt + its emitted tokens, emit_start set) at
        the head of the admission queue."""
        out, self._preempted = self._preempted, []
        return out

    def _allocate_under_pressure(self, s: int, n: int):
        """Allocate `n` blocks for slot `s`, preempting the lowest-
        progress slot under pool pressure (requeue, not deadlock);
        returns None when `s` itself lost the pool race (it has been
        preempted and released)."""
        got = self.pool.allocator.allocate(n)
        while got is None:
            # radix LRU leaves go first — cache-only references, no
            # re-prefill cost — before any live slot is preempted
            if self._radix is not None and self._radix.evict_lru():
                self.radix_evictions_total += 1
                got = self.pool.allocator.allocate(n)
                continue
            victim = self._lowest_progress_active()
            self._preempt(victim)
            if victim == s:
                return None            # s itself lost the pool race
            got = self.pool.allocator.allocate(n)
        return got

    def _grow_block_tables(self, tokens_by_slot=None):
        """Pre-dispatch block grants: every active slot gets the blocks
        its write window `[pos, pos + tokens)` will cross into (lazy
        growth, incremental allocation), and any window block the slot
        does NOT own exclusively — refcount > 1: still mapped by the
        shared-prefix cache or another slot — is FORKED first
        (copy-on-first-write: fresh block, device copy, the slot's
        reference on the shared source dropped). Admission forks the
        common case eagerly; this pass is the invariant's enforcement
        point — no dispatch may ever write a block another holder
        reads. Under pool pressure the lowest-progress slot is evicted
        (requeue, not deadlock); check_budget guarantees a slot left
        alone in the pool can always finish — prefix-pinned blocks
        excluded — so this terminates with every surviving slot fully
        granted and exclusively owning its window."""
        J = self.steps_per_dispatch
        fork_pairs = []
        for s in range(self.n_slots):
            if not self.active[s] or self.slots[s] is None:
                continue
            slot = self.slots[s]
            if tokens_by_slot is None:
                tokens = min(J, int(self.remaining[s]))
            else:
                tokens = int(tokens_by_slot.get(s, 0))
            if tokens < 1:
                continue
            needed = blocks_needed(int(self.pos[s]) + tokens,
                                   self.block_len)
            have = len(slot.blocks)
            if needed > have:
                got = self._allocate_under_pressure(s, needed - have)
                if got is None or self.slots[s] is None:
                    continue
                slot.blocks.extend(got)
                self.block_tables[s, have:needed] = got
                self.block_grants_total += len(got)
            # copy-on-first-write fork of shared write-window blocks
            first_b = int(self.pos[s]) // self.block_len
            last_b = (int(self.pos[s]) + tokens - 1) // self.block_len
            for bi in range(first_b, min(last_b + 1, len(slot.blocks))):
                src = slot.blocks[bi]
                if self.pool.allocator.refcount(src) <= 1:
                    continue
                got = self._allocate_under_pressure(s, 1)
                if got is None or self.slots[s] is None:
                    break              # s lost the pool race mid-fork
                dst = got[0]
                fork_pairs.append((s, src, dst))
                slot.blocks[bi] = dst
                self.block_tables[s, bi] = dst
                self.pool.allocator.free([src])   # drop OUR reference
                self.block_grants_total += 1
        # a slot preempted AFTER recording a fork has already freed its
        # dst block (maybe even re-granted to a later slot this pass) —
        # copying into it now would corrupt the new owner; only live
        # slots' forks dispatch
        fork_pairs = [(src, dst) for s, src, dst in fork_pairs
                      if self.slots[s] is not None]
        if fork_pairs:
            self._run_fork(fork_pairs)

    # ------------------------------------------------------------- decode
    def step(self, *, speculate: Optional[bool] = None,
             proposers: Optional[tuple] = None
             ) -> Tuple[Dict[int, List[int]], List[int]]:
        """One continuous-batching dispatch: every active slot advances
        up to `steps_per_dispatch` tokens — or, with `speculative=k`
        configured (and `speculate` not overridden to False by the
        scheduler's accept-rate policy), up to k tokens through ONE
        k-position score dispatch (`_spec_step`). Returns ({slot:
        [tokens emitted this dispatch]}, [slots that finished and were
        released]). Under incremental allocation, slots whose next
        writes cross a block boundary are granted blocks first — and
        pool pressure preempts the lowest-progress slot into
        `drain_preempted()` instead of deadlocking."""
        if speculate is None:
            speculate = self.spec_k is not None
        if speculate and self.spec_k:
            return self._spec_step(proposers=proposers)
        if (self.allocation == "incremental" or self._prefixes
                or self._radix is not None):
            # upfront allocation never grows, but the CoW fork pass
            # (shared write-window blocks) must still run
            self._grow_block_tables()
        if not self.active.any():
            return {}, []
        # two static program variants: the greedy-only decode skips the
        # sampling chain (sort + threefry) — picked whenever no sampled
        # request is in flight, the common serving case
        if (self.temp[self.active] > 0).any():
            if self._decode_full is None:
                self._decode_full = self._build_decode(greedy_only=False)
            decode = self._decode_full
        else:
            if self._decode_greedy is None:
                self._decode_greedy = self._build_decode(greedy_only=True)
            decode = self._decode_greedy
        kv, toks, valids = decode(
            self._params, self.net.net_state, self.pool.kv,
            jnp.asarray(self.block_tables), jnp.asarray(self.last_token),
            jnp.asarray(self.pos), jnp.asarray(self.remaining),
            jnp.asarray(self.keys), jnp.asarray(self.emit_idx),
            jnp.asarray(self.temp), jnp.asarray(self.top_p))
        self.pool.kv = kv
        toks = np.asarray(toks)                     # [J, S]
        valids = np.asarray(valids)
        taken = valids.sum(axis=0).astype(np.int32)  # [S] tokens emitted
        act = self.active
        # ledger: the decode chunk touched J*S token-positions; emitted
        # tokens on live lanes are useful, idle/finished lanes and the
        # tail past each lane's budget are pad_waste
        n_useful = int(np.where(act, taken, 0).sum())
        self.goodput.account(
            useful=n_useful,
            pad_waste=int(toks.shape[0]) * int(toks.shape[1]) - n_useful)
        last_idx = np.clip(taken - 1, 0, None)
        self.last_token = np.where(
            act & (taken > 0), toks[last_idx, np.arange(toks.shape[1])],
            self.last_token)
        self.pos = self.pos + np.where(act, taken, 0)
        self.emit_idx = self.emit_idx + np.where(act, taken, 0)
        self.remaining = self.remaining - np.where(act, taken, 0)
        emitted: Dict[int, List[int]] = {}
        finished = []
        for i in np.flatnonzero(act):
            i = int(i)
            emitted[i] = [int(t) for t in toks[valids[:, i], i]]
            self.slots[i].emitted += int(taken[i])
            self.slots[i].pos = int(self.pos[i])
            if self.spec_k:
                self.slots[i].history.extend(emitted[i])
            if self.remaining[i] <= 0:
                finished.append(i)
                self._release(i)
        return emitted, finished

    # ------------------------------------------------- speculative decode
    def _propose(self, s: int, max_draft: int) -> List[int]:
        """Self-drafting proposer: an n-gram suffix cache over the
        slot's own token history (prompt + emitted). The continuation
        that followed the MOST RECENT earlier occurrence of the
        current suffix n-gram is the draft — longest n first
        (`spec_max_ngram`), nothing matched proposes nothing (the slot
        decodes one verified token, exactly vanilla). Free of model
        cost by construction: the 'draft model' is a numpy substring
        search, and the acceptance oracle (the target's own argmax)
        makes any bad draft cost only its rejected lanes.

        Host cost per call is a full-history windowed scan —
        O(len(history) x spec_max_ngram) numpy compares — which the
        page budget bounds at max_total_tokens per slot per dispatch;
        an incremental ngram -> last-occurrence map updated at
        history.extend would make it O(spec_max_ngram) if budgets
        grow past the point where this scan shows up in TPOT."""
        if max_draft <= 0:
            return []
        hist = self.slots[s].history
        L = len(hist)
        if L < 2:
            return []
        h = np.asarray(hist, np.int64)
        for n in range(min(self.spec_max_ngram, L - 1), 0, -1):
            suffix = h[L - n:]
            # candidate occurrences must end before the history's last
            # token so at least one continuation token exists
            win = np.lib.stride_tricks.sliding_window_view(h[:L - 1], n)
            hits = np.flatnonzero((win == suffix).all(axis=1))
            if hits.size:
                # most recent occurrence WITH a full-depth continuation
                # wins; matches hugging the end of history only offer a
                # one-or-two-token draft (on a converged cycle — the
                # common serving tail — that recency bias was measured
                # to cap acceptance near 0.4 where a full-depth draft
                # of the same cycle scores near 1.0)
                full = hits[hits + n + max_draft <= L]
                i = int(full[-1]) if full.size else int(hits[-1])
                cont = h[i + n:i + n + max_draft]
                if cont.size:
                    return [int(t) for t in cont]
        return []

    def _spec_step(self, proposers: Optional[tuple] = None
                   ) -> Tuple[Dict[int, List[int]], List[int]]:
        """One speculative dispatch: the proposer drafts up to k-1
        tokens per greedy slot, ONE k-position score dispatch
        (`_get_score`) runs the target over [last_token, d1..d_{k-1}],
        and the host accepts the longest draft prefix the target's own
        argmax agrees with — the first disagreement truncates and
        emits the TARGET's token, so the emitted stream is the
        target's greedy stream bit-for-bit no matter what the drafts
        were (rejected lanes' K/V writes sit beyond the advanced `pos`
        and are overwritten by the dispatch that reaches them, the
        same write-before-read discipline the garbage block rests on).
        Sampled slots: with `spec_sampled=False` (the default) they
        ride the same dispatch at depth 1 — their token comes from the
        `chosen` sampling tail, untouched by speculation and bit-equal
        to the spec-free engine. With `spec_sampled=True` they take
        drafts too and the acceptance oracle is REJECTION SAMPLING
        (`rejection_sample_drafts`): each emitted token is marginally
        a vanilla sample from the target's filtered distribution — a
        distributional contract, not a bit one. `proposers` (the
        scheduler's per-proposer arbitration) restricts which draft
        backends may run this dispatch; None allows all configured.
        Emits 1..k tokens per slot per dispatch."""
        if not self.active.any():
            return {}, []
        K = self.spec_k
        S = self.n_slots
        allow_ngram = proposers is None or "ngram" in proposers
        allow_trunc = (self._draft_plan is not None
                       and (proposers is None or "truncated" in proposers))
        token_mat = np.zeros((S, K), np.int32)
        n_valid = np.zeros(S, np.int32)
        by_proposer: Dict[int, str] = {}
        trunc_slots: List[Tuple[int, int]] = []
        for s in np.flatnonzero(self.active):
            s = int(s)
            token_mat[s, 0] = self.last_token[s]
            if self.temp[s] > 0 and not self.spec_sampled:
                n_valid[s] = 1          # sampling has no greedy oracle
                continue
            depth = int(min(K, self.remaining[s]))
            draft = self._propose(s, depth - 1) if allow_ngram else []
            if draft:
                by_proposer[s] = "ngram"
                n_valid[s] = 1 + len(draft)
                token_mat[s, 1:1 + len(draft)] = draft
            elif allow_trunc and depth >= 2:
                # n-gram came up empty — the truncated-layer drafter
                # takes the slot (drafts filled in below, after its
                # write window is granted)
                trunc_slots.append((s, depth))
                n_valid[s] = depth
            else:
                n_valid[s] = 1
        if trunc_slots:
            # grant (and CoW-fork) the drafting slots' FULL windows
            # first: the truncated pass writes draft K/V into the
            # slot's own not-yet-committed positions [pos, pos+d-2],
            # all of which the verify dispatch below rewrites with
            # full-model K/V (write-before-read)
            self._grow_block_tables(dict(trunc_slots))
            trunc_slots = [(s, d) for s, d in trunc_slots
                           if self.slots[s] is not None
                           and self.active[s]]
        if trunc_slots:
            drafts = self._run_draft(trunc_slots)
            for s, d in trunc_slots:
                by_proposer[s] = "truncated"
                token_mat[s, 1:d] = drafts[:d - 1, s]
        # grant (and CoW-fork) each slot's write window [pos,
        # pos+n_valid) — pool pressure preempts exactly like the
        # chunked path
        self._grow_block_tables(
            {int(s): int(n_valid[s]) for s in np.flatnonzero(self.active)})
        n_valid = np.where(self.active, n_valid, 0).astype(np.int32)
        if not self.active.any():
            return {}, []
        greedy_only = not bool((self.temp[self.active] > 0).any())
        use_rs = self.spec_sampled and not greedy_only
        score = self._get_score(K, "rs" if use_rs else greedy_only)
        out = score(
            self._params, self.net.net_state, self.pool.kv,
            jnp.asarray(self.block_tables), jnp.asarray(token_mat),
            jnp.asarray(self.pos), jnp.asarray(n_valid),
            jnp.asarray(self.keys), jnp.asarray(self.emit_idx),
            jnp.asarray(self.temp), jnp.asarray(self.top_p))
        if use_rs:
            kv, greedy_mat, n_acc, final = out
            n_acc, final = np.asarray(n_acc), np.asarray(final)
            chosen = None
        else:
            kv, greedy_mat, chosen = out
            chosen = np.asarray(chosen)
        self.pool.kv = kv
        greedy_mat = np.asarray(greedy_mat)
        self.spec_dispatches_total += 1
        # ledger: the score program touched S*K token-positions; per
        # slot, emitted tokens are useful, valid-but-rejected draft
        # lanes are spec_rejected, positions past n_valid (and whole
        # inactive rows) are pad_waste — tallied in the accept loop
        gp_useful = 0
        gp_rejected = 0
        emitted: Dict[int, List[int]] = {}
        finished = []
        for s in np.flatnonzero(self.active):
            s = int(s)
            v = int(n_valid[s])
            prop = by_proposer.get(s)
            if self.temp[s] > 0:
                if use_rs:
                    # rejection sampling: the first n_acc drafts
                    # survived their u < q_t(d) tests; `final` is the
                    # residual resample at the divergence (or the
                    # bonus token when every draft survived)
                    acc = min(int(n_acc[s]), v - 1)
                    toks = [int(token_mat[s, j])
                            for j in range(1, 1 + acc)] + [int(final[s])]
                else:
                    toks = [int(chosen[s])]
                if v > 1:
                    self.spec_proposed_total += v - 1
                    self.spec_accepted_total += len(toks) - 1
            else:
                # acceptance: draft j survives iff it EQUALS the
                # target's argmax after position j-1; the first miss
                # truncates and the target's token takes its place
                row = greedy_mat[s]
                toks = [int(row[0])]
                for j in range(1, v):
                    if int(token_mat[s, j]) != toks[-1]:
                        break
                    toks.append(int(row[j]))
                self.spec_proposed_total += v - 1
                self.spec_accepted_total += len(toks) - 1
            if prop is not None and v > 1:
                self.spec_proposed_by[prop] += v - 1
                self.spec_accepted_by[prop] += len(toks) - 1
            n = len(toks)
            gp_useful += n
            gp_rejected += v - n
            self.spec_emitted_total += n
            self.pos[s] += n
            self.emit_idx[s] += n
            self.remaining[s] -= n
            self.last_token[s] = toks[-1]
            slot = self.slots[s]
            slot.emitted += n
            slot.pos = int(self.pos[s])
            slot.history.extend(toks)
            emitted[s] = toks
            if self.remaining[s] <= 0:
                finished.append(s)
                self._release(s)
        self.goodput.account(
            useful=gp_useful, spec_rejected=gp_rejected,
            pad_waste=S * K - gp_useful - gp_rejected)
        return emitted, finished

    # ------------------------------------------------------------ evict
    def evict(self, slot: int):
        """Mid-stream eviction (cancel/timeout): free the slot and its
        blocks immediately; the pool pages become garbage the moment
        the table row is retired (no device work — the next gather by
        a reusing sequence overwrites them via its own prefill)."""
        if self.slots[slot] is None:
            raise ValueError(f"slot {slot} is not in use")
        self._release(slot)

    def _release(self, slot: int):
        s = self.slots[slot]
        self.pool.allocator.free(s.blocks)
        self.slots[slot] = None
        self.active[slot] = False
        self.remaining[slot] = 0
        self.block_tables[slot] = GARBAGE_BLOCK

    # --------------------------------------- disaggregation handoff
    def export_handoff(self, slot: int) -> Tuple[dict, np.ndarray]:
        """Serialize one LIVE slot for a prefill→decode handoff: the
        paged block table is the handoff format — the returned header
        is the slot's full host state, the array its granted K/V
        blocks gathered from the pool and stacked
        ``[n_layers, 2, n_blocks, block_len, heads, head_dim]`` in the
        pool's compute dtype. `wire.encode_handoff` puts both on the
        ND4T wire; a decode engine's `adopt_handoff` rebuilds the slot
        bit-identically (shared/CoW source blocks are gathered by
        VALUE, so the adopting pool always gets private copies).

        The exporting engine is left untouched — the caller releases
        the slot with `evict()` once the handoff is safely delivered
        (at-least-once: a failed send keeps the slot decodable here)."""
        s = self.slots[slot]
        if s is None:
            raise ValueError(f"slot {slot} is not in use")
        if not self.active[slot]:
            raise ValueError(
                f"slot {slot} already finished — nothing to hand off")
        idx = np.asarray(s.blocks, np.int64)
        per_layer = []
        for (k, v) in self.pool.kv:
            per_layer.append(np.stack([np.asarray(k)[idx],
                                       np.asarray(v)[idx]]))
        kv = np.stack(per_layer)
        header = {
            "request_id": s.request_id,
            "prompt_len": int(s.prompt_len),
            "n_tokens": int(s.n_tokens),
            "pos": int(self.pos[slot]),
            "remaining": int(self.remaining[slot]),
            "emitted": int(s.emitted),
            "emit_base": int(s.emit_base),
            "emit_idx": int(self.emit_idx[slot]),
            "last_token": int(self.last_token[slot]),
            "history": [int(t) for t in s.history],
            "keys": [int(x) for x in self.keys[slot]],
            "temperature": float(self.temp[slot]),
            "top_p": float(self.top_p[slot]),
            "block_len": int(self.block_len),
            "n_layers": len(self.pool.kv),
        }
        return header, kv

    def adopt_handoff(self, header: dict, kv) -> int:
        """Adopt a handed-off slot: allocate private blocks, scatter
        the K/V payload into the pool, and rebuild the host slot state
        so the next `step()` continues the stream bit-identically to
        the exporting engine having kept it (the PR-9 parity contract
        extended across the wire). Raises ValueError on a pool-shape/
        dtype mismatch, RuntimeError when no slot or blocks are free
        (the caller's backpressure signal — nothing is mutated)."""
        kv = np.asarray(kv)
        L = len(self.pool.kv)
        k0 = self.pool.kv[0][0]
        if kv.ndim != 6 or kv.shape[0] != L or kv.shape[1] != 2:
            raise ValueError(
                f"handoff K/V shape {kv.shape} does not match this "
                f"pool's {L} layers")
        if int(header["block_len"]) != self.block_len:
            raise ValueError(
                f"handoff block_len {header['block_len']} != engine "
                f"block_len {self.block_len}")
        if tuple(kv.shape[3:]) != tuple(k0.shape[1:]):
            raise ValueError(
                f"handoff block shape {kv.shape[3:]} != pool block "
                f"shape {tuple(k0.shape[1:])}")
        if np.dtype(kv.dtype) != np.dtype(k0.dtype):
            raise ValueError(
                f"handoff dtype {kv.dtype} != pool compute dtype "
                f"{k0.dtype} — a silent cast would break bit-parity")
        slot = next((i for i, s in enumerate(self.slots) if s is None),
                    None)
        if slot is None:
            raise RuntimeError("no free slot to adopt the handoff")
        n_blocks = int(kv.shape[2])
        blocks = self._alloc_admit(n_blocks)
        if blocks is None:
            raise RuntimeError(
                f"pool cannot grant {n_blocks} blocks for the handoff "
                f"({self.pool.free_blocks} free)")
        bidx = jnp.asarray(np.asarray(blocks, np.int32))
        new_kv = []
        for l, (k, v) in enumerate(self.pool.kv):
            new_kv.append((k.at[bidx].set(jnp.asarray(kv[l, 0])),
                           v.at[bidx].set(jnp.asarray(kv[l, 1]))))
        self.pool.kv = tuple(new_kv)
        s = Slot(header.get("request_id"), blocks,
                 int(header["prompt_len"]), int(header["n_tokens"]),
                 emit_base=int(header.get("emit_base") or 0),
                 history=[int(t) for t in (header.get("history") or [])])
        s.emitted = int(header["emitted"])
        s.pos = int(header["pos"])
        self.slots[slot] = s
        self.block_tables[slot] = GARBAGE_BLOCK
        self.block_tables[slot, :len(blocks)] = blocks
        self.pos[slot] = int(header["pos"])
        self.remaining[slot] = int(header["remaining"])
        self.emit_idx[slot] = int(
            header.get("emit_idx", s.emit_base + s.emitted))
        self.last_token[slot] = int(header["last_token"])
        self.keys[slot] = np.asarray(header.get("keys") or [0, 0],
                                     np.uint32)
        self.temp[slot] = float(header.get("temperature") or 0.0)
        tp = header.get("top_p")
        self.top_p[slot] = 1.0 if tp is None else float(tp)
        self.active[slot] = int(header["remaining"]) > 0
        self.block_grants_total += n_blocks
        return slot
