"""Paged continuous-batching decode engine.

The device-program half of the serving tier (the threaded scheduler
lives in serving/server.py): a fixed set of `n_slots` serving slots
advances ONE token per jitted dispatch over the paged KV pool — static
slot count means ONE XLA program no matter which sequences are in
flight; empty slots decode garbage into the reserved block and are
masked out on the host.

Per dispatch:

- `decode_step(params, state, kv, block_tables, token_ids, slot_state)
  -> (kv', next_ids, done_flags)` — embedding -> per-slot positional
  signal -> paged transformer blocks -> per-position softmax, then
  greedy argmax or per-slot sampled next token. Inputs ride h2d once
  per step (they are a few `[S]` vectors + the `[S, max_blocks]`
  tables); the pools stay device-resident (donated where the backend
  supports it).
- admission prefills a prompt through the SAME cached `prefill` jit
  `generate()` uses (zoo/transformer.get_prefill), then scatters the
  filled monolithic carries into the sequence's pool blocks — so
  prefill numerics are `generate()`'s by construction.

Decode-parity contract (docs/SERVING.md): for the same prompt and
sampling config, the token stream is identical to whole-batch
`generate()` — greedy is exact (test-enforced bit-equality); sampled
mode derives token t's key as `fold_in(request_key, t)`, which makes a
request's stream deterministic REGARDLESS of what else is in flight
(whole-batch `generate()` draws per-batch, so its sampled streams
change with batch composition — the serving tier deliberately does
not reproduce that).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.nd.donation import donate_argnums
from deeplearning4j_tpu.nn.layers.recurrent import BaseRecurrentLayer
from deeplearning4j_tpu.nn.layers.transformer import (
    PositionalEncodingLayer,
    TransformerEncoderBlock,
    stream_budget,
)
from deeplearning4j_tpu.serving.paged import (
    GARBAGE_BLOCK,
    PagedKVPool,
    blocks_needed,
)


class Slot:
    """Host mirror of one serving slot's in-flight sequence."""

    __slots__ = ("request_id", "blocks", "prompt_len", "n_tokens",
                 "emitted", "pos")

    def __init__(self, request_id, blocks, prompt_len, n_tokens):
        self.request_id = request_id
        self.blocks = blocks
        self.prompt_len = prompt_len
        self.n_tokens = n_tokens
        self.emitted = 0
        self.pos = prompt_len


class PagedDecodeEngine:
    """Continuous-batching decode over a `PagedKVPool`.

    Synchronous and single-threaded by design — every method must be
    called from one scheduler thread (serving/server.py owns that
    thread; tests drive the engine directly for determinism).

    `top_k` is engine-static (lax.top_k needs a static k — same
    constraint `generate()` documents); temperature and top_p are
    per-request traced values, so mixed greedy/sampled batches share
    the one decode program.
    """

    def __init__(self, net, *, n_slots: int = 8, n_blocks: int = 64,
                 block_len: int = 16, top_k: Optional[int] = None,
                 steps_per_dispatch: int = 1):
        if not getattr(net, "_initialized", False):
            net.init()
        self.net = net
        self.n_slots = int(n_slots)
        self.steps_per_dispatch = int(steps_per_dispatch)
        if self.steps_per_dispatch < 1:
            raise ValueError(
                f"steps_per_dispatch must be >= 1; got {steps_per_dispatch}")
        self.top_k = None if top_k is None else int(top_k)
        if self.n_slots < 1:
            raise ValueError(f"n_slots must be >= 1; got {n_slots}")
        budget = stream_budget(net.layers)
        if budget is None:
            raise ValueError(
                "net has no bounded stream budget (no TransformerEncoder"
                "Block / PositionalEncodingLayer) — nothing to page")
        if budget % block_len != 0:
            raise ValueError(
                f"block_len {block_len} must divide the stream budget "
                f"{budget} (KV cache_len / positional max_len): the "
                f"gathered page view must have the same length as the "
                f"monolithic cache for decode parity")
        vocab = getattr(net.layers[-1], "n_out", None)
        if self.top_k is not None and not (1 <= self.top_k <=
                                           (vocab or self.top_k)):
            raise ValueError(f"top_k must be in [1, vocab={vocab}]; "
                             f"got {top_k}")
        self.max_blocks = budget // int(block_len)
        self.max_total_tokens = budget
        self.pool = PagedKVPool(net, n_blocks, block_len)
        self.block_len = int(block_len)
        # a serving "plan": how each layer participates in the paged
        # decode walk. Input preprocessors would silently change the
        # math mid-walk — reject loudly (the zoo LMs have none).
        if net.conf.input_preprocessors:
            raise ValueError(
                "paged decode does not support input preprocessors "
                f"(found at {sorted(net.conf.input_preprocessors)})")
        self._plan: List[Tuple] = []
        pool_j = 0
        for i, layer in enumerate(net.layers):
            if isinstance(layer, TransformerEncoderBlock):
                self._plan.append(("block", i, pool_j))
                pool_j += 1
            elif isinstance(layer, PositionalEncodingLayer):
                self._plan.append(("pos", i))
            elif isinstance(layer, BaseRecurrentLayer):
                raise ValueError(
                    f"layer {i} ({type(layer).__name__}) carries "
                    "recurrent state but has no paged decode path")
            else:
                self._plan.append(("plain", i))
        # host slot state (uploaded per step; a few [S] vectors)
        S = self.n_slots
        self.block_tables = np.zeros((S, self.max_blocks), np.int32)
        self.pos = np.zeros(S, np.int32)
        self.active = np.zeros(S, bool)
        self.remaining = np.zeros(S, np.int32)
        self.emit_idx = np.zeros(S, np.int32)
        self.last_token = np.zeros(S, np.int32)
        self.keys = np.zeros((S, 2), np.uint32)
        self.temp = np.zeros(S, np.float32)
        self.top_p = np.ones(S, np.float32)
        self.slots: List[Optional[Slot]] = [None] * S
        self._decode_full = None      # greedy + sampling chain
        self._decode_greedy = None    # argmax only (no sort/rng ops)
        self._admit_finish = {}       # k -> fused write-pages+first-token

    # ------------------------------------------------------------ queries
    @property
    def free_slots(self) -> int:
        return sum(1 for s in self.slots if s is None)

    @property
    def active_slots(self) -> int:
        return int(self.active.sum())

    @property
    def free_blocks(self) -> int:
        return self.pool.free_blocks

    def can_admit(self, prompt_len: int, n_tokens: int) -> bool:
        return (any(s is None for s in self.slots)
                and blocks_needed(prompt_len + n_tokens, self.block_len)
                <= self.pool.free_blocks)

    def check_budget(self, prompt_len: int, n_tokens: int):
        """Reject requests that can NEVER be admitted — distinct from
        `can_admit` (not right now): over the per-sequence page budget,
        or needing more blocks than the whole pool owns (a queued
        request waiting on capacity that cannot exist would deadlock
        its consumer)."""
        total = prompt_len + n_tokens
        if n_tokens < 1:
            raise ValueError(f"n_tokens must be >= 1; got {n_tokens}")
        if total > self.max_total_tokens:
            raise ValueError(
                f"prompt ({prompt_len}) + n_tokens ({n_tokens}) = {total} "
                f"exceeds the per-sequence page budget "
                f"{self.max_total_tokens} (max_blocks {self.max_blocks} x "
                f"block_len {self.block_len}); this request can never be "
                f"admitted — rebuild the model with a larger max_len")
        usable = self.pool.n_blocks - 1      # id 0 is the garbage block
        if blocks_needed(total, self.block_len) > usable:
            raise ValueError(
                f"request needs {blocks_needed(total, self.block_len)} "
                f"pool blocks but the pool only has {usable} usable "
                f"(n_blocks {self.pool.n_blocks} incl. the reserved "
                f"garbage block); it can never be admitted — grow "
                f"n_blocks or shorten the request")

    # ----------------------------------------------------------- sampling
    def _sample_ids(self, probs, keys, emit_idx, temp, top_p,
                    greedy_only: bool = False):
        """Next token per row of `probs` [S, V]: greedy argmax where
        temp == 0 (bit-identical to `generate(temperature=0)`), else
        the same log/clip/filter/categorical chain `generate` runs —
        with a PER-SLOT key folded by emit index, the serving rng
        contract. `greedy_only=True` (a STATIC program variant the
        scheduler picks when no sampled request is in flight) skips
        the sort/threefry chain entirely — measured at ~half the
        decode chunk on the CPU sandbox."""
        greedy_ids = jnp.argmax(probs, axis=-1).astype(jnp.int32)
        if greedy_only:
            return greedy_ids
        from deeplearning4j_tpu.zoo.transformer import filter_logits
        safe_t = jnp.where(temp > 0, temp, 1.0)
        logits = jnp.log(jnp.clip(probs, 1e-9, None)) / safe_t[:, None]
        # generate()'s own filter body, with per-slot traced p
        # (p=1.0 keeps everything)
        logits = filter_logits(logits, self.top_k, top_p[:, None])
        skeys = jax.vmap(jax.random.fold_in)(keys, emit_idx)
        sampled = jax.vmap(jax.random.categorical)(skeys, logits)
        return jnp.where(temp > 0, sampled.astype(jnp.int32), greedy_ids)

    # ------------------------------------------------------ jit builders
    def _build_decode(self, greedy_only: bool):
        net, layers, plan = self.net, self.net.layers, self._plan
        J = self.steps_per_dispatch

        def one_token(params, state, kv, block_tables, token_ids, pos,
                      keys, emit_idx, temp, top_p):
            h = token_ids[:, None]            # [S, 1] int ids
            kv = list(kv)
            for entry in plan:
                kind, i = entry[0], entry[1]
                layer = layers[i]
                lp = params.get(str(i), {})
                ls = state.get(str(i), {})
                if kind == "plain":
                    h, _ = layer.forward(lp, ls, h, train=False, rng=None)
                elif kind == "pos":
                    h, _ = layer.forward_at_positions(lp, ls, h, pos)
                else:
                    j = entry[2]
                    k_pool, v_pool = kv[j]
                    h, k_pool, v_pool = layer.forward_paged(
                        lp, h, k_pool, v_pool, block_tables, pos)
                    kv[j] = (k_pool, v_pool)
            probs = h[:, -1]                   # [S, V]
            return tuple(kv), self._sample_ids(probs, keys, emit_idx,
                                               temp, top_p,
                                               greedy_only=greedy_only)

        def decode_step(params, state, kv, block_tables, token_ids,
                        pos, remaining, keys, emit_idx, temp, top_p):
            """`steps_per_dispatch` micro-steps fused into ONE program
            via lax.scan: host round-trip and dispatch overhead
            amortize over J tokens x S slots (the continuous-batching
            counterpart of `generate()`'s fused decode scan). A slot
            finishing mid-chunk keeps decoding — into its own pages or
            the garbage block, never another slot's — and the `valids`
            mask tells the host which emissions are real. J=1 is the
            admit-every-token schedule the scheduler defaults to."""
            params = net.dtype.cast_params(params)

            def micro(carry, _):
                kv, tok, pos, rem, emit = carry
                kv, nxt = one_token(params, state, kv, block_tables,
                                    tok, pos, keys, emit, temp, top_p)
                return ((kv, nxt, pos + 1, rem - 1, emit + 1),
                        (nxt, rem > 0))

            carry = (kv, token_ids, pos, remaining, emit_idx)
            (kv, _, _, _, _), (toks, valids) = jax.lax.scan(
                micro, carry, None, length=J)
            return kv, toks, valids            # [J, S] each

        return jax.jit(decode_step, donate_argnums=donate_argnums(2))

    def _build_admit_finish(self, k: int, greedy_only: bool):
        """One fused dispatch completing a k-wide admission wave:
        scatter every sequence's monolithic prefill K/V into its pool
        pages AND sample the wave's first tokens from the prefill
        probs. Separate per-request dispatches here were measured to
        cost as much as a whole `generate()` call each on the CPU
        sandbox — admission overhead is exactly what the sequential
        baseline pays, so it must be amortized for continuous batching
        to win."""
        bl = self.block_len

        def admit_finish(kv, rows, block_carries, probs, keys, temp,
                         top_p):
            # rows [k, max_rows]; block_carries: per layer (k_cache,
            # v_cache) with leading dim k; probs [k, V]
            out = []
            for (k_pool, v_pool), (k_cache, v_cache) in zip(
                    kv, block_carries):
                C = k_cache.shape[1]
                shape = (k * (C // bl), bl) + k_cache.shape[2:]
                flat_rows = rows[:, :C // bl].reshape(-1)
                out.append((
                    k_pool.at[flat_rows].set(
                        k_cache.reshape(shape).astype(k_pool.dtype)),
                    v_pool.at[flat_rows].set(
                        v_cache.reshape(shape).astype(v_pool.dtype)),
                ))
            firsts = self._sample_ids(probs, keys,
                                      jnp.zeros((k,), jnp.int32),
                                      temp, top_p,
                                      greedy_only=greedy_only)
            return tuple(out), firsts

        return jax.jit(admit_finish, donate_argnums=donate_argnums(0))

    # ---------------------------------------------------------- admission
    def admit(self, prompt_ids, n_tokens: int, *, request_id=None,
              temperature: float = 0.0, top_p: Optional[float] = None,
              rng=None):
        """Single-request admission (a k=1 `admit_many` wave). Returns
        (slot index, first emitted token, done) or None when capacity
        can't take the request right now."""
        out = self.admit_many([dict(prompt_ids=prompt_ids,
                                    n_tokens=n_tokens,
                                    request_id=request_id,
                                    temperature=temperature,
                                    top_p=top_p, rng=rng)])
        return out[0] if out else None

    def admit_many(self, requests: List[dict]):
        """Admission wave: prefill up to len(requests) SAME-LENGTH
        prompts as one batch through the cached `prefill` jit
        (zoo/transformer.get_prefill — `generate()`'s own program, so
        prefill numerics are its by construction), then one fused
        dispatch writes all their pool pages and samples all their
        first tokens. Requests beyond the wave's slot/block capacity
        are left unadmitted (the returned list is a PREFIX of the
        input — FIFO order preserved).

        Each request dict: prompt_ids, n_tokens, and optionally
        request_id, temperature, top_p, rng. Returns
        [(slot, first_token, done), ...] for the admitted prefix."""
        if not requests:
            return []
        wave = []
        try:
            P = None
            for r in requests:
                prompt = np.asarray(r["prompt_ids"])
                if prompt.ndim == 2 and prompt.shape[0] == 1:
                    prompt = prompt[0]
                if prompt.ndim != 1 or prompt.size == 0:
                    raise ValueError(
                        f"prompt must be a non-empty 1-D id sequence; "
                        f"got shape {prompt.shape}")
                if P is None:
                    P = int(prompt.shape[0])
                elif int(prompt.shape[0]) != P:
                    break    # caller groups by length; stop the wave
                n_tokens = int(r["n_tokens"])
                self.check_budget(P, n_tokens)
                slot = next((i for i, s in enumerate(self.slots)
                             if s is None
                             and all(i != w[0] for w in wave)),
                            None)
                if slot is None:
                    break
                nb = blocks_needed(P + n_tokens, self.block_len)
                blocks = self.pool.allocator.allocate(nb)
                if blocks is None:
                    break
                wave.append((slot, prompt, n_tokens, nb, blocks, r))
            if not wave:
                return []
            return self._admit_wave(wave)
        except Exception:
            # a mid-wave failure (validation of a later request, a
            # prefill/admit dispatch error) must return the wave's
            # already-allocated blocks — no Slot owns them yet, so
            # _release could never recover them and the pool would
            # shrink permanently (capacity leak -> eventual silent
            # starvation of every later admission). Entries a Slot DID
            # take ownership of (partial bookkeeping) keep theirs —
            # the normal release path frees those.
            for slot, _, _, _, blocks, _ in wave:
                s = self.slots[slot]
                if s is None or s.blocks is not blocks:
                    try:
                        self.pool.allocator.free(blocks)
                    except ValueError:
                        pass   # already back in the pool
            raise

    def _admit_wave(self, wave):
        k = len(wave)
        # pad the wave to the next power of two: every distinct batch
        # width costs a prefill + admit_finish COMPILE, and free-slot
        # counts vary chunk to chunk — unquantized widths were measured
        # as a compile storm that dwarfed the serving itself. Dummy
        # rows repeat the last prompt, scatter only into the garbage
        # block, and their sampled firsts are discarded.
        k2 = 1
        while k2 < k:
            k2 *= 2

        net = self.net
        from deeplearning4j_tpu.zoo.transformer import get_prefill
        prefill = get_prefill(net)
        carries = {str(i): layer.init_carry(k2, net.dtype.compute_dtype)
                   for i, layer in enumerate(net.layers)
                   if isinstance(layer, BaseRecurrentLayer)}
        prompts = np.stack([w[1] for w in wave]
                           + [wave[-1][1]] * (k2 - k)).astype(np.int32)
        probs, carries = prefill(net.params, net.net_state,
                                 jnp.asarray(prompts), carries)

        block_carries = [carries[str(i)] for i in self.pool.layer_indices]
        max_rows = max(c[0].shape[1] // self.block_len
                       for c in block_carries)
        rows = np.full((k2, max_rows), GARBAGE_BLOCK, np.int32)
        keys = np.zeros((k2, 2), np.uint32)
        temps = np.zeros(k2, np.float32)
        top_ps = np.ones(k2, np.float32)
        for j, (slot, prompt, n_tokens, nb, blocks, r) in enumerate(wave):
            rows[j, :nb] = blocks
            if r.get("rng") is not None:
                keys[j] = np.asarray(r["rng"], np.uint32).reshape(2)
            temps[j] = r.get("temperature") or 0.0
            p = r.get("top_p")
            top_ps[j] = 1.0 if p is None else p
        # all-greedy waves skip the sampling chain (sort + threefry) on
        # the TTFT-critical path — same static-variant split the
        # decode program uses
        greedy = not bool((temps > 0).any())
        fin = self._admit_finish.get((k2, greedy))
        if fin is None:
            fin = self._admit_finish[(k2, greedy)] = \
                self._build_admit_finish(k2, greedy)
        self.pool.kv, firsts = fin(
            self.pool.kv, jnp.asarray(rows),
            tuple((c[0], c[1]) for c in block_carries), probs,
            jnp.asarray(keys), jnp.asarray(temps), jnp.asarray(top_ps))
        firsts = np.asarray(firsts)

        out = []
        for j, (slot, prompt, n_tokens, nb, blocks, r) in enumerate(wave):
            first = int(firsts[j])
            done = n_tokens == 1
            self.slots[slot] = Slot(r.get("request_id"), blocks,
                                    len(prompt), n_tokens)
            self.slots[slot].emitted = 1
            self.block_tables[slot] = GARBAGE_BLOCK
            self.block_tables[slot, :nb] = blocks
            self.pos[slot] = len(prompt)
            self.remaining[slot] = n_tokens - 1
            self.emit_idx[slot] = 1
            self.last_token[slot] = first
            self.keys[slot] = keys[j]
            self.temp[slot] = temps[j]
            self.top_p[slot] = top_ps[j]
            self.active[slot] = not done
            if done:
                self._release(slot)
            out.append((slot, first, done))
        return out

    # ------------------------------------------------------------- decode
    def step(self) -> Tuple[Dict[int, List[int]], List[int]]:
        """One continuous-batching dispatch: every active slot advances
        up to `steps_per_dispatch` tokens. Returns ({slot: [tokens
        emitted this dispatch]}, [slots that finished and were
        released])."""
        if not self.active.any():
            return {}, []
        # two static program variants: the greedy-only decode skips the
        # sampling chain (sort + threefry) — picked whenever no sampled
        # request is in flight, the common serving case
        if (self.temp[self.active] > 0).any():
            if self._decode_full is None:
                self._decode_full = self._build_decode(greedy_only=False)
            decode = self._decode_full
        else:
            if self._decode_greedy is None:
                self._decode_greedy = self._build_decode(greedy_only=True)
            decode = self._decode_greedy
        kv, toks, valids = decode(
            self.net.params, self.net.net_state, self.pool.kv,
            jnp.asarray(self.block_tables), jnp.asarray(self.last_token),
            jnp.asarray(self.pos), jnp.asarray(self.remaining),
            jnp.asarray(self.keys), jnp.asarray(self.emit_idx),
            jnp.asarray(self.temp), jnp.asarray(self.top_p))
        self.pool.kv = kv
        toks = np.asarray(toks)                     # [J, S]
        valids = np.asarray(valids)
        taken = valids.sum(axis=0).astype(np.int32)  # [S] tokens emitted
        act = self.active
        last_idx = np.clip(taken - 1, 0, None)
        self.last_token = np.where(
            act & (taken > 0), toks[last_idx, np.arange(toks.shape[1])],
            self.last_token)
        self.pos = self.pos + np.where(act, taken, 0)
        self.emit_idx = self.emit_idx + np.where(act, taken, 0)
        self.remaining = self.remaining - np.where(act, taken, 0)
        emitted: Dict[int, List[int]] = {}
        finished = []
        for i in np.flatnonzero(act):
            i = int(i)
            emitted[i] = [int(t) for t in toks[valids[:, i], i]]
            self.slots[i].emitted += int(taken[i])
            self.slots[i].pos = int(self.pos[i])
            if self.remaining[i] <= 0:
                finished.append(i)
                self._release(i)
        return emitted, finished

    # ------------------------------------------------------------ evict
    def evict(self, slot: int):
        """Mid-stream eviction (cancel/timeout): free the slot and its
        blocks immediately; the pool pages become garbage the moment
        the table row is retired (no device work — the next gather by
        a reusing sequence overwrites them via its own prefill)."""
        if self.slots[slot] is None:
            raise ValueError(f"slot {slot} is not in use")
        self._release(slot)

    def _release(self, slot: int):
        s = self.slots[slot]
        self.pool.allocator.free(s.blocks)
        self.slots[slot] = None
        self.active[slot] = False
        self.remaining[slot] = 0
        self.block_tables[slot] = GARBAGE_BLOCK
