"""Paged KV-cache pool: fixed-size blocks + host-side accounting.

The serving tier's memory plane (ROADMAP "production inference tier";
the design TF-Serving layered over the TF runtime, PAPERS.md §serving):
instead of one monolithic `[cache_len, H, Dh]` buffer pinned per
sequence for its whole lifetime, K/V live in a shared pool of
fixed-size blocks `[n_blocks, block_len, H, Dh]` per transformer
layer. A sequence owns `ceil((prompt + n_tokens) / block_len)` blocks,
addressed through a per-slot block table — so `stream_budget` becomes
a POOL-capacity question (how many sequences fit at once) instead of a
per-sequence clamp, and a finished sequence's blocks immediately serve
the next admission.

Split of responsibilities:

- device: the block pools (one (K, V) pair per transformer block
  layer, all dtype = the net's compute dtype) and the gather/scatter
  attention path (`MultiHeadAttention.forward_with_paged_cache`);
- host: free/used accounting (`BlockAllocator`) and the block tables,
  which ride h2d once per scheduler step.

Block id 0 is RESERVED as the garbage block: inactive slots and block-
table padding point at it, so masked scatter lanes always have a legal
target and freed blocks can be retired from a table without reshaping
anything. The allocator never hands it out.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import jax.numpy as jnp

from deeplearning4j_tpu.nn.layers.transformer import TransformerEncoderBlock

GARBAGE_BLOCK = 0


def blocks_needed(total_tokens: int, block_len: int) -> int:
    """Blocks a sequence of `total_tokens` (prompt + generated) owns."""
    return -(-int(total_tokens) // int(block_len))


class BlockAllocator:
    """Host-side free-list over pool block ids 1..n_blocks-1 (id 0 is
    the reserved garbage block). Allocation is all-or-nothing: a
    request either gets its full block set or stays queued — partial
    grants would deadlock two half-admitted sequences against each
    other. LIFO reuse keeps freshly-freed blocks hot.

    Grants are REFCOUNTED (the copy-on-write shared-prefix plane,
    docs/SERVING.md): `allocate` hands out blocks at refcount 1;
    `share` takes an additional reference on already-granted blocks
    (multiple slots — and the server's prefix cache — mapping the same
    physical prefix block); `free` drops one reference and only
    returns the block to the free list when the last holder lets go.
    The double-free guard generalizes: dropping a reference a block
    does not carry is the same bug class as the PR-9 free-list
    double-append, and raises the same way."""

    def __init__(self, n_blocks: int):
        if n_blocks < 2:
            raise ValueError(
                f"need at least 2 pool blocks (1 usable + the reserved "
                f"garbage block); got {n_blocks}")
        self.n_blocks = int(n_blocks)
        # pop() order: 1, 2, 3, ... for a fresh pool
        self._free: List[int] = list(range(self.n_blocks - 1, 0, -1))
        self._refs: Dict[int, int] = {}      # granted block -> refcount

    @property
    def free_blocks(self) -> int:
        return len(self._free)

    @property
    def used_blocks(self) -> int:
        return (self.n_blocks - 1) - len(self._free)

    @property
    def shared_blocks(self) -> int:
        """Physical blocks currently mapped by more than one holder
        (refcount > 1) — the `serving_prefix_blocks_shared` gauge."""
        return sum(1 for r in self._refs.values() if r > 1)

    def refcount(self, block: int) -> int:
        return self._refs.get(int(block), 0)

    def allocate(self, n: int) -> Optional[List[int]]:
        """`n` block ids (each at refcount 1), or None if the pool
        can't cover the request right now (caller keeps it queued)."""
        if n <= 0:
            raise ValueError(f"allocate(n={n})")
        if n > len(self._free):
            return None
        out = [self._free.pop() for _ in range(n)]
        for b in out:
            self._refs[b] = 1
        return out

    def share(self, blocks: List[int]):
        """Take one more reference on each of `blocks` — they must be
        granted already (a share of a free block would alias whatever
        sequence the free list hands it to next)."""
        for b in blocks:
            b = int(b)
            if self._refs.get(b, 0) < 1:
                raise ValueError(
                    f"share of block {b} which is not granted (free or "
                    f"out of range) — a stale grant reference")
        for b in blocks:
            self._refs[int(b)] += 1

    def free(self, blocks: List[int]):
        # validate the WHOLE batch before mutating anything: a double-
        # free halfway through a list must not leave the allocator in a
        # half-freed state (the PR-9 guard, extended to refcounts —
        # a list naming one block more times than it holds references
        # is the same bug)
        need: Dict[int, int] = {}
        for b in blocks:
            b = int(b)
            if not (0 < b < self.n_blocks):
                raise ValueError(f"freeing invalid block id {b}")
            need[b] = need.get(b, 0) + 1
        for b, n in need.items():
            if self._refs.get(b, 0) < n:
                raise ValueError(f"double-free of block {b}")
        for b in blocks:
            b = int(b)
            self._refs[b] -= 1
            if self._refs[b] == 0:
                del self._refs[b]
                self._free.append(b)


class _RadixNode:
    """One edge of the radix tree: a run of BLOCK-ALIGNED token chunks
    and the pool blocks holding their K/V. `tokens` is always a
    multiple of `block_len` long and `blocks[j]` holds tokens
    `tokens[j*bl:(j+1)*bl]`; children are keyed by the first block's
    token tuple (unique among siblings — any two edges sharing a full
    first block get factored by a split, and edges differing within
    the first block differ in the key)."""

    __slots__ = ("tokens", "blocks", "children", "parent", "last_used",
                 "pinned")

    def __init__(self, tokens, blocks, parent):
        self.tokens = tokens
        self.blocks = blocks
        self.children: Dict[tuple, "_RadixNode"] = {}
        self.parent = parent
        self.last_used = 0
        self.pinned = False


class RadixPrefixCache:
    """Radix tree over block-aligned token chunks — automatic
    mid-prompt K/V dedup across ALL admissions (the `prefix_cache=
    "radix"` engine mode, docs/SERVING.md), replacing the manual
    exact-match-from-token-0 `register_prefix` contract.

    Every admission's prompt is `match()`ed against the tree (longest
    block-aligned shared prefix → those blocks are `share()`d to the
    new slot, copy-on-write discipline unchanged) and `insert()`ed on
    the way in (the slot's fully-written prompt blocks become tree
    edges, with the cache holding its OWN allocator reference on each
    — a finished slot's release leaves the prefix resident). Matching
    and splitting happen only at block boundaries, so a radix hit
    never needs a mid-block fork or cached next-token probs: the
    engine caps the match below the full prompt and runs its ordinary
    suffix-extension prefill for the remainder.

    Eviction is LRU over UNPINNED LEAVES (`evict_lru()`): the engine
    calls it under pool pressure BEFORE preempting live slots, and the
    cache drops its reference — a block still mapped by an active slot
    survives at the slot's refcount (the same last-holder-frees rule
    every other release rides). Nothing here is pinned capacity:
    `check_budget` ignores radix-held blocks because they are
    reclaimable on demand."""

    def __init__(self, allocator: BlockAllocator, block_len: int):
        self.alloc = allocator
        self.block_len = int(block_len)
        self.root = _RadixNode((), [], None)
        self._n_nodes = 0
        self._clock = 0

    def _tick(self) -> int:
        self._clock += 1
        return self._clock

    @property
    def nodes(self) -> int:
        """Edge count (root excluded) — the `serving_radix_nodes`
        gauge."""
        return self._n_nodes

    def _iter_nodes(self):
        stack = list(self.root.children.values())
        while stack:
            n = stack.pop()
            stack.extend(n.children.values())
            yield n

    @property
    def held_blocks(self) -> int:
        return sum(len(n.blocks) for n in self._iter_nodes())

    @property
    def evictable_blocks(self) -> int:
        """Blocks that would return to the free list if the whole
        unpinned tree were evicted (cache is the only holder)."""
        return sum(1 for n in self._iter_nodes() if not n.pinned
                   for b in n.blocks if self.alloc.refcount(b) == 1)

    def match(self, tokens) -> Tuple[int, List[int]]:
        """Longest block-aligned cached prefix of `tokens`: returns
        `(n_matched_tokens, blocks)` — the caller `share()`s the
        blocks onto the admitted slot. Touches the path for LRU."""
        t = tuple(int(x) for x in tokens)
        bl = self.block_len
        now = self._tick()
        node, i, out = self.root, 0, []
        while len(t) - i >= bl:
            child = node.children.get(t[i:i + bl])
            if child is None:
                break
            m = 0
            while (m < len(child.blocks) and i + (m + 1) * bl <= len(t)
                   and child.tokens[m * bl:(m + 1) * bl]
                   == t[i + m * bl:i + (m + 1) * bl]):
                m += 1
            child.last_used = now
            out.extend(child.blocks[:m])
            i += m * bl
            if m < len(child.blocks):
                break
            node = child
        return i, out

    def insert(self, tokens, blocks) -> int:
        """Insert the fully-written prompt blocks of a just-admitted
        slot. `tokens[:len(blocks)*block_len]` must be the tokens those
        blocks hold. Shared portions already in the tree are skipped
        (the tree keeps ITS blocks); the diverging suffix becomes a new
        edge the cache takes its own references on. Returns the number
        of newly referenced blocks."""
        bl = self.block_len
        t = tuple(int(x) for x in tokens)
        nb = min(len(t) // bl, len(blocks))
        t = t[:nb * bl]
        now = self._tick()
        node, i, bi = self.root, 0, 0
        while bi < nb:
            key = t[i:i + bl]
            child = node.children.get(key)
            if child is None:
                new_blocks = [int(b) for b in blocks[bi:nb]]
                self.alloc.share(new_blocks)
                leaf = _RadixNode(t[i:], new_blocks, node)
                leaf.last_used = now
                node.children[key] = leaf
                self._n_nodes += 1
                return len(new_blocks)
            m = 0
            while (m < len(child.blocks) and bi + m < nb
                   and child.tokens[m * bl:(m + 1) * bl]
                   == t[i + m * bl:i + (m + 1) * bl]):
                m += 1
            child.last_used = now
            if m == len(child.blocks):
                node, i, bi = child, i + m * bl, bi + m
                continue
            if bi + m == nb:
                return 0          # prompt ends inside the edge: cached
            node = self._split(child, m)
            i, bi = i + m * bl, bi + m
        return 0

    def _split(self, child: "_RadixNode", m: int) -> "_RadixNode":
        """Split `child` at block boundary `m` (0 < m < blocks): the
        upper part becomes a new interior node, `child` keeps the
        tail."""
        bl = self.block_len
        parent = child.parent
        upper = _RadixNode(child.tokens[:m * bl], child.blocks[:m], parent)
        upper.last_used = child.last_used
        upper.pinned = child.pinned
        parent.children[child.tokens[:bl]] = upper
        child.tokens = child.tokens[m * bl:]
        child.blocks = child.blocks[m:]
        child.parent = upper
        upper.children[child.tokens[:bl]] = child
        self._n_nodes += 1
        return upper

    def evict_lru(self) -> int:
        """Drop the cache's references on the least-recently-used
        unpinned LEAF. Returns how many block references were released
        (0 = nothing evictable). Blocks still mapped by a live slot
        stay granted at the slot's refcount."""
        best = None
        for n in self._iter_nodes():
            if n.children or n.pinned:
                continue
            if best is None or n.last_used < best.last_used:
                best = n
        if best is None:
            return 0
        del best.parent.children[best.tokens[:self.block_len]]
        self.alloc.free(best.blocks)
        self._n_nodes -= 1
        return len(best.blocks)

    def clear(self) -> int:
        """Release every cache-held reference (drain/evict-all). The
        tree rebuilds from traffic — fleet swap successors start here."""
        dropped = 0
        for n in list(self._iter_nodes()):
            self.alloc.free(n.blocks)
            dropped += len(n.blocks)
        self.root.children.clear()
        self._n_nodes = 0
        return dropped


class PagedKVPool:
    """The per-layer block pools for one model + the shared allocator.

    `kv` is a flat tuple of (k_pool, v_pool) pairs — one per
    TransformerEncoderBlock in layer order — shaped
    `[n_blocks, block_len, n_heads, head_dim]` in the net's compute
    dtype (the same dtype `init_carry` gives the monolithic caches, so
    prefill copies are exact). It is a plain pytree: jitted programs
    take it as an argument and return the updated pools."""

    def __init__(self, net, n_blocks: int, block_len: int):
        if block_len < 1:
            raise ValueError(f"block_len must be >= 1; got {block_len}")
        self.block_len = int(block_len)
        self.n_blocks = int(n_blocks)
        self.layer_indices = [i for i, l in enumerate(net.layers)
                              if isinstance(l, TransformerEncoderBlock)]
        if not self.layer_indices:
            raise ValueError(
                "PagedKVPool needs at least one TransformerEncoderBlock "
                f"layer; got {[type(l).__name__ for l in net.layers]}")
        dtype = net.dtype.compute_dtype
        kv = []
        for i in self.layer_indices:
            layer = net.layers[i]
            shape = (self.n_blocks, self.block_len, layer.n_heads,
                     layer.n_in // layer.n_heads)
            kv.append((jnp.zeros(shape, dtype), jnp.zeros(shape, dtype)))
        self.kv: Tuple = tuple(kv)
        self.allocator = BlockAllocator(self.n_blocks)

    @property
    def free_blocks(self) -> int:
        return self.allocator.free_blocks

    @property
    def used_blocks(self) -> int:
        return self.allocator.used_blocks

    def device_bytes(self) -> int:
        total = 0
        for k, v in self.kv:
            total += k.size * k.dtype.itemsize + v.size * v.dtype.itemsize
        return total
