"""Horizontal serving: multi-process model replicas.

One logical model, N worker processes. Each `ReplicaWorker` wraps a
`GenerationServer` behind a TCP socket speaking the fleet wire frames
(`DLFQ` requests in, `DLFR` token chunks out, length-prefixed via
`wire.send_frame`/`recv_frame`) and registers with the
`parallel/elastic.py` coordinator as a SERVING member — it advertises
capacity (queue depth, outstanding tokens, tok/s EWMA) on every
heartbeat instead of training ranks, and the coordinator's
generation-numbered membership gives every router one consistent
replica view across joins and deaths (`elastic.serving_directory`).

Router side, `ReplicaSet` polls the directory and keeps one
`ReplicaClient` connection per live replica; `FleetRouter.submit`
balances across them LEAST-LOADED FIRST and sheds only when the whole
set is projected past SLO (serving/router.py). A worker dying
mid-stream surfaces as a typed `ReplicaLostError` carrying the request
id, the last reply ordinal received, and the partial tokens — the
signal the router's migration logic acts on: nothing-received requests
resubmit verbatim to a survivor, partial streams continue as
prompt+received with emit_start (same-version replicas only, the
continuation contract).

Warmup cost across replicas is amortized by the persistent XLA compile
cache: point every worker's `DL4J_COMPILE_CACHE_DIR` at one shared
volume and replica N's warmup replays replica 1's compilations.
"""

from __future__ import annotations

import json
import logging
import os
import socket
import subprocess
import sys
import threading
import time
import uuid
from concurrent.futures import Future
from typing import Dict, List, Optional, Tuple

import numpy as np

from deeplearning4j_tpu.serving import wire

log = logging.getLogger("deeplearning4j_tpu.serving.replica")


def _hard_close(sock: socket.socket) -> None:
    """shutdown() then close(): close() alone does NOT send FIN while
    another thread is blocked in recv() on the same socket (the
    in-flight syscall keeps the kernel socket referenced), so a peer
    would never observe the death — shutdown() tears the connection
    down immediately and wakes every blocked reader."""
    try:
        sock.shutdown(socket.SHUT_RDWR)
    except OSError:
        pass
    try:
        sock.close()
    except OSError:
        pass


class ReplicaLostError(RuntimeError):
    """A replica worker died (or its connection broke) with requests in
    flight. Carries everything retry/migration logic needs: the request
    id, ``last_seq`` (last reply ordinal received; -1 = none) and
    ``tokens`` (the partial stream). Zero tokens received means the
    request never started — resubmit verbatim anywhere; a partial
    stream continues as prompt+received with ``emit_start`` on a
    same-version replica (bit-consistent by the continuation
    contract)."""

    def __init__(self, message: str, *, request_id: Optional[str] = None,
                 last_seq: int = -1, tokens=None,
                 replica: Optional[str] = None):
        super().__init__(message)
        self.request_id = request_id
        self.last_seq = int(last_seq)
        self.tokens = [int(t) for t in (tokens or [])]
        self.replica = replica


# =====================================================================
# client side
# =====================================================================
class ReplicaStream:
    """Client face of one replica-served generation — `TokenStream`'s
    future face over a socket: `.tokens` grows as chunks land,
    `result()` blocks on the terminal frame, producer-side
    `t_submit`/`t_first` timestamps feed TTFT."""

    def __init__(self, request_id: str, model: str, n_tokens: int,
                 replica: Optional[str] = None):
        self._fut: Future = Future()
        self.request_id = request_id
        self.model = model
        self.version: Optional[int] = None
        self.n_tokens = int(n_tokens)
        self.tokens: List[int] = []
        self.t_submit = time.monotonic()
        self.t_first: Optional[float] = None
        self.t_last: Optional[float] = None
        self.last_seq = -1
        self.replica = replica

    def _on_reply(self, header: dict, chunk) -> None:
        seq = int(header.get("seq", 0))
        if seq > self.last_seq:
            self.last_seq = seq
            if len(chunk):
                now = time.monotonic()
                if self.t_first is None:
                    self.t_first = now
                self.t_last = now
                self.tokens.extend(int(t) for t in chunk)
        if header.get("version") is not None:
            self.version = int(header["version"])
        if header.get("done") and not self._fut.done():
            err = wire.reply_error(header)
            if err is not None:
                self._fut.set_exception(err)
            else:
                self._fut.set_result(list(self.tokens))

    def _lose(self, exc: BaseException) -> None:
        if not self._fut.done():
            self._fut.set_exception(exc)

    def done(self) -> bool:
        return self._fut.done()

    def result(self, timeout: Optional[float] = None) -> np.ndarray:
        return np.asarray(self._fut.result(timeout), np.int32)


class ReplicaClient:
    """One connection to one replica worker. Thread-safe submits; a
    single reader thread demultiplexes reply frames onto streams by
    request id. Any connection failure fails EVERY in-flight stream
    with `ReplicaLostError` — the typed signal migration acts on."""

    def __init__(self, host: str, port: int, *,
                 token: Optional[str] = None,
                 connect_timeout_s: float = 5.0):
        self.host, self.port = host, int(port)
        self.token = token or f"{host}:{port}"
        self._sock = socket.create_connection((host, self.port),
                                              timeout=connect_timeout_s)
        self._sock.settimeout(None)
        self._wlock = threading.Lock()
        self._lock = threading.Lock()
        self._streams: Dict[str, ReplicaStream] = {}
        self._closed = False
        self._reader = threading.Thread(
            target=self._read_loop, daemon=True,
            name=f"replica-client-{self.token}")
        self._reader.start()

    @property
    def closed(self) -> bool:
        return self._closed

    def submit(self, model: str, prompt_ids, n_tokens: int, *,
               temperature: float = 0.0, top_p: Optional[float] = None,
               rng=None, emit_start: int = 0,
               request_id: Optional[str] = None,
               trace_id: Optional[str] = None) -> ReplicaStream:
        rid = request_id or uuid.uuid4().hex
        frame = wire.encode_request(model, rid, prompt_ids, n_tokens,
                                    temperature=temperature, top_p=top_p,
                                    rng=rng, emit_start=emit_start,
                                    trace_id=trace_id)
        stream = ReplicaStream(rid, model, n_tokens, replica=self.token)
        with self._lock:
            if self._closed:
                raise ReplicaLostError(
                    f"replica {self.token} connection is closed",
                    request_id=rid, replica=self.token)
            self._streams[rid] = stream
        try:
            with self._wlock:
                wire.send_frame(self._sock, frame)
        except OSError as e:
            with self._lock:
                self._streams.pop(rid, None)
            self._fail_all(e)
            raise ReplicaLostError(
                f"replica {self.token} died at submit ({e})",
                request_id=rid, replica=self.token) from e
        return stream

    def _read_loop(self) -> None:
        try:
            while True:
                data = wire.recv_frame(self._sock)
                header, chunk = wire.decode_reply(data)
                rid = header["request_id"]
                with self._lock:
                    stream = self._streams.get(rid)
                    if header.get("done"):
                        self._streams.pop(rid, None)
                if stream is not None:
                    stream._on_reply(header, chunk)
        except (ConnectionError, OSError) as e:
            self._fail_all(e)
        except wire.WireFormatError as e:
            # a corrupt stream cannot be resynchronized — same fate as
            # a dead peer, but the typed cause rides along
            self._fail_all(e)

    def _fail_all(self, cause: BaseException) -> None:
        with self._lock:
            if self._closed:
                return
            self._closed = True
            streams, self._streams = self._streams, {}
        _hard_close(self._sock)
        for rid, s in streams.items():
            s._lose(ReplicaLostError(
                f"replica {self.token} lost mid-stream after seq "
                f"{s.last_seq} of request {rid} ({cause!r})",
                request_id=rid, last_seq=s.last_seq, tokens=s.tokens,
                replica=self.token))

    def close(self) -> None:
        self._fail_all(ConnectionError("client closed"))


class ReplicaSet:
    """Router-side replica view for one model: polls the elastic
    coordinator's `status()` (member info refreshes every heartbeat —
    fresher than the committed plan), reconciles one `ReplicaClient`
    per live serving member, and exposes `(token, client, meta)`
    backends with their advertised load gauges. A member leaving the
    directory closes its client, which fails its in-flight streams
    with `ReplicaLostError` — death detection and load reporting ride
    the SAME membership plane."""

    def __init__(self, coordinator_address: str, model: str, *,
                 refresh_s: float = 0.1, io_timeout_s: float = 2.0):
        self.coordinator_address = coordinator_address
        self.model = str(model)
        self.refresh_s = float(refresh_s)
        self.io_timeout_s = float(io_timeout_s)
        self.generation = 0
        self._lock = threading.Lock()
        self._clients: Dict[str, ReplicaClient] = {}
        self._meta: Dict[str, dict] = {}
        self._last_refresh = 0.0

    def refresh(self, force: bool = False) -> None:
        now = time.monotonic()
        with self._lock:
            if not force and now - self._last_refresh < self.refresh_s:
                return
            self._last_refresh = now
        from deeplearning4j_tpu.parallel.elastic import (
            retry_request,
            serving_directory,
        )
        try:
            status = retry_request(
                self.coordinator_address, {"op": "status"},
                timeout=self.io_timeout_s, attempts=2)["status"]
        except Exception as e:  # noqa: BLE001 — keep last known view
            log.warning("replica directory refresh failed (%s); keeping "
                        "the last known view", e)
            return
        d = serving_directory(status, self.model)
        live = {}
        for r in d["replicas"]:
            if r["port"] is None:
                continue
            live[r["token"]] = r
        # connect OUTSIDE the lock: ReplicaClient() is a blocking
        # connect with a multi-second timeout, and one unreachable
        # replica must not stall backends() — and every submit — for
        # that long
        with self._lock:
            need = []
            for tok, r in live.items():
                c = self._clients.get(tok)
                if c is None or c.closed:
                    need.append((tok, r["host"], r["port"]))
        connected = []
        for tok, host, port in need:
            try:
                connected.append((tok, ReplicaClient(host, port,
                                                     token=tok)))
            except OSError as e:
                log.warning("replica %s unreachable at %s:%s (%s)",
                            tok, host, port, e)
        evicted: List[ReplicaClient] = []
        with self._lock:
            self.generation = d["generation"]
            self._meta = live
            for tok, c in connected:
                old = self._clients.get(tok)
                if old is not None and not old.closed:
                    # a concurrent refresh connected first; keep its
                    # client (it may already carry in-flight streams)
                    evicted.append(c)
                else:
                    self._clients[tok] = c
            for tok in list(self._clients):
                if tok not in live:
                    # evicted from the membership: fail its streams NOW
                    # (typed) instead of letting them ride a dead socket
                    evicted.append(self._clients.pop(tok))
        # close AFTER releasing the lock: close() fails the client's
        # in-flight streams synchronously on THIS thread, and a failed
        # stream's migration path re-enters refresh()/backends() on
        # this same ReplicaSet — closing under the non-reentrant lock
        # deadlocks the whole replica set (the re-entrant refresh now
        # just returns early via the throttle with the view installed
        # above)
        for c in evicted:
            c.close()

    def backends(self) -> List[Tuple[str, ReplicaClient, dict]]:
        with self._lock:
            return [(tok, c, dict(self._meta.get(tok, {})))
                    for tok, c in self._clients.items() if not c.closed]

    def close(self) -> None:
        with self._lock:
            clients, self._clients = self._clients, {}
        for c in clients.values():
            c.close()


# =====================================================================
# worker side
# =====================================================================
class ReplicaWorker:
    """One serving replica: a `GenerationServer` behind a TCP request
    plane, registered with the elastic coordinator as a serving member.
    Load gauges (`queue_depth`, `outstanding_tokens`, `ewma_tok_s`,
    `open_streams`) refresh on every heartbeat via the member info
    channel AND publish locally as `serving_replica_*` gauge families
    {model=, replica=} — with `monitor.federate` enabled they flow to
    the coordinator like every other federated family (PR-15)."""

    def __init__(self, net, *, model: str = "model", version: int = 1,
                 host: str = "127.0.0.1", port: int = 0,
                 coordinator: Optional[str] = None,
                 token: Optional[str] = None,
                 heartbeat_interval_s: float = 0.25,
                 warmup_prompt_len: Optional[int] = None,
                 warmup_tokens: int = 2,
                 poll_s: float = 0.002,
                 **server_kw):
        from deeplearning4j_tpu.serving.server import GenerationServer
        self.model = str(model)
        self.version = int(version)
        self.poll_s = float(poll_s)
        self.heartbeat_interval_s = float(heartbeat_interval_s)
        server_kw.setdefault("name", self.model)
        self.server = GenerationServer(net, **server_kw)
        if warmup_prompt_len is not None:
            self.server.warmup(warmup_prompt_len, warmup_tokens)
        self._lsock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._lsock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._lsock.bind((host, int(port)))
        self._lsock.listen(64)
        self.host, self.port = self._lsock.getsockname()[:2]
        self.token = token or f"replica-{self.model}-{self.port}"
        self.coordinator = coordinator
        self._elastic = None
        self._running = False
        self._threads: List[threading.Thread] = []
        self._conns: List[socket.socket] = []
        self._conn_lock = threading.Lock()
        self._metrics_cache = None

    # ------------------------------------------------------------ lifecycle
    def start(self) -> "ReplicaWorker":
        if self._running:
            return self
        self._running = True
        self.server.start()
        if self.coordinator is not None:
            from deeplearning4j_tpu.parallel.elastic import ElasticClient
            self._elastic = ElasticClient(
                self.coordinator, self.token,
                heartbeat_interval_s=self.heartbeat_interval_s)
            self._elastic.register_serving(
                model=self.model, host=self.host, port=self.port,
                info=dict(self._load_info(), version=self.version))
            self._elastic.federate_metrics(worker=self.token)
            self._elastic.start_heartbeats()
        for target, name in ((self._accept_loop, "accept"),
                             (self._gauge_loop, "gauges")):
            t = threading.Thread(target=target, daemon=True,
                                 name=f"replica-{self.token}-{name}")
            t.start()
            self._threads.append(t)
        log.info("replica %s serving %s v%d on %s:%d", self.token,
                 self.model, self.version, self.host, self.port)
        return self

    def stop(self) -> None:
        if not self._running:
            return
        self._running = False
        if self._elastic is not None:
            self._elastic.leave("replica stopped")
            self._elastic.stop()
        try:
            self._lsock.close()
        except OSError:
            pass
        with self._conn_lock:
            conns, self._conns = self._conns, []
        for c in conns:
            _hard_close(c)
        for t in self._threads:
            t.join(timeout=5)
        self.server.stop()

    # --------------------------------------------------------- load gauges
    def _load_info(self) -> dict:
        srv = self.server
        return {
            "queue_depth": int(srv.queue_depth()),
            "outstanding_tokens": int(srv._outstanding_tokens()
                                      + srv.queued_tokens),
            "ewma_tok_s": float(srv._ewma_tok_s or 0.0),
            "open_streams": int(srv.open_streams),
            "n_slots": int(srv.engine.n_slots),
        }

    def _metrics(self):
        from deeplearning4j_tpu import monitor

        def build(reg):
            lab = dict(model=self.model, replica=self.token)
            return {
                "queue": reg.gauge(
                    "serving_replica_queue_depth",
                    "admission queue depth of one serving replica",
                    **lab),
                "outstanding": reg.gauge(
                    "serving_replica_outstanding_tokens",
                    "projected decode work owed by one replica", **lab),
                "tok_s": reg.gauge(
                    "serving_replica_tok_s",
                    "token-throughput EWMA of one replica", **lab),
                "open": reg.gauge(
                    "serving_replica_open_streams",
                    "streams open on one replica", **lab),
            }

        from deeplearning4j_tpu import monitor as m
        return m.resolve_cached_metrics(self, "_metrics_cache", build)

    def _gauge_loop(self) -> None:
        while self._running:
            info = self._load_info()
            if self._elastic is not None:
                self._elastic.set_info(**info)
            m = self._metrics()
            if m is not None:
                m["queue"].set(info["queue_depth"])
                m["outstanding"].set(info["outstanding_tokens"])
                m["tok_s"].set(info["ewma_tok_s"])
                m["open"].set(info["open_streams"])
            time.sleep(self.heartbeat_interval_s)

    # ------------------------------------------------------- request plane
    def _accept_loop(self) -> None:
        while self._running:
            try:
                conn, _ = self._lsock.accept()
            except OSError:
                return                           # listener closed: stop()
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            with self._conn_lock:
                self._conns.append(conn)
            t = threading.Thread(target=self._serve_conn, args=(conn,),
                                 daemon=True,
                                 name=f"replica-{self.token}-conn")
            t.start()
            self._threads.append(t)

    def _serve_conn(self, conn: socket.socket) -> None:
        """One connection: a reader half ingesting DLFQ frames and a
        relay half streaming DLFR chunks back. All socket WRITES happen
        on the relay half (single writer — no interleaved frames);
        submit failures are queued as error entries the relay sends."""
        active: Dict[str, dict] = {}
        lock = threading.Lock()
        reader_done = threading.Event()

        def reader():
            try:
                while self._running:
                    data = wire.recv_frame(conn)
                    rid = None
                    try:
                        header, prompt = wire.decode_request(data)
                        rid = header["request_id"]
                        stream = self.server.generate_async(
                            prompt, int(header["n_tokens"]),
                            temperature=header.get("temperature") or 0.0,
                            top_p=header.get("top_p"),
                            rng=header.get("rng"),
                            emit_start=int(header.get("emit_start") or 0))
                        ent = {"stream": stream, "cursor": 0, "seq": 0}
                    except wire.WireFormatError:
                        if rid is None:
                            log.exception("replica %s: undecodable "
                                          "frame dropped", self.token)
                            continue
                        ent = {"stream": None, "seq": 0,
                               "error": wire.WireFormatError(
                                   "malformed request frame")}
                    except Exception as e:  # noqa: BLE001 — shed /
                        # validation errors fail THAT request only
                        if rid is None:
                            log.exception("replica %s: request failed "
                                          "before it had an id",
                                          self.token)
                            continue
                        ent = {"stream": None, "seq": 0, "error": e}
                    with lock:
                        active[rid] = ent
            except (ConnectionError, OSError, wire.WireFormatError):
                pass
            finally:
                reader_done.set()

        rt = threading.Thread(target=reader, daemon=True,
                              name=f"replica-{self.token}-read")
        rt.start()
        try:
            self._relay(conn, active, lock, reader_done)
        finally:
            _hard_close(conn)
            # client gone: cancel what it will never read, so a dead
            # connection does not pin slots against live traffic
            with lock:
                orphans = [e["stream"] for e in active.values()
                           if e.get("stream") is not None]
                active.clear()
            for s in orphans:
                if not s._fut.done():
                    s.cancel()
            rt.join(timeout=5)

    def _relay(self, conn, active, lock, reader_done) -> None:
        """The router `_relay_loop` discipline over a socket: freeze a
        chunk before its first send, advance only after success, send
        the terminal frame only when every chunk is out."""
        while self._running:
            with lock:
                items = list(active.items())
            if not items and reader_done.is_set():
                return
            progressed = False
            for rid, ent in items:
                stream = ent.get("stream")
                try:
                    if stream is None:
                        wire.send_frame(conn, wire.encode_reply(
                            rid, ent["seq"], [], done=True,
                            model=self.model, version=self.version,
                            error=ent["error"]))
                        with lock:
                            active.pop(rid, None)
                        progressed = True
                        continue
                    toks = stream.tokens
                    if len(toks) > ent["cursor"]:
                        end = len(toks)
                        wire.send_frame(conn, wire.encode_reply(
                            rid, ent["seq"], toks[ent["cursor"]:end],
                            done=False, model=self.model,
                            version=self.version))
                        ent["cursor"] = end
                        ent["seq"] += 1
                        progressed = True
                    if (stream._fut.done()
                            and ent["cursor"] == len(stream.tokens)):
                        exc = stream._fut.exception(timeout=0)
                        wire.send_frame(conn, wire.encode_reply(
                            rid, ent["seq"], [], done=True,
                            model=self.model, version=self.version,
                            error=exc))
                        with lock:
                            active.pop(rid, None)
                        progressed = True
                except (ConnectionError, OSError):
                    return                       # peer gone: cleanup above
            if not progressed:
                time.sleep(self.poll_s)


# =====================================================================
# replica fleet management (the autoscaler's actuator)
# =====================================================================
class ReplicaManager:
    """Grow/shrink the replica count for one model. `factory()` builds
    and starts one replica (a `ReplicaWorker`, a subprocess handle from
    `spawn_replica`, anything with `.stop()`); shrink stops the
    NEWEST replica first (the oldest carries the warmed caches and the
    longest EWMA history). `FleetAutoscaler(replicas=...)` drives this
    from the same pressure signal that scales slots."""

    def __init__(self, factory, *, min_replicas: int = 1,
                 max_replicas: int = 4):
        if min_replicas < 1 or max_replicas < min_replicas:
            raise ValueError(
                f"need 1 <= min_replicas <= max_replicas; got "
                f"[{min_replicas}, {max_replicas}]")
        self.factory = factory
        self.min_replicas = int(min_replicas)
        self.max_replicas = int(max_replicas)
        self._replicas: List[object] = []
        self._lock = threading.Lock()

    def count(self) -> int:
        with self._lock:
            return len(self._replicas)

    def grow(self) -> bool:
        with self._lock:
            if len(self._replicas) >= self.max_replicas:
                return False
        handle = self.factory()
        with self._lock:
            self._replicas.append(handle)
        return True

    def shrink(self) -> bool:
        with self._lock:
            if len(self._replicas) <= self.min_replicas:
                return False
            handle = self._replicas.pop()
        handle.stop()
        return True

    def scale_to(self, n: int) -> int:
        n = max(self.min_replicas, min(self.max_replicas, int(n)))
        while self.count() < n:
            if not self.grow():
                break
        while self.count() > n:
            if not self.shrink():
                break
        return self.count()

    def stop(self) -> None:
        with self._lock:
            replicas, self._replicas = self._replicas, []
        for h in replicas:
            try:
                h.stop()
            except Exception:  # noqa: BLE001 — teardown is best-effort
                log.exception("replica stop failed")


# =====================================================================
# subprocess entry
# =====================================================================
class ReplicaProcess:
    """Handle on one `spawn_replica` subprocess."""

    def __init__(self, proc: subprocess.Popen, host: str, port: int,
                 token: str):
        self.proc = proc
        self.host, self.port, self.token = host, int(port), token

    def alive(self) -> bool:
        return self.proc.poll() is None

    def kill(self) -> None:
        """Hard kill — the replica-death drill's murder weapon."""
        self.proc.kill()
        self.proc.wait(timeout=30)

    def stop(self) -> None:
        if self.proc.poll() is None:
            self.proc.terminate()
            try:
                self.proc.wait(timeout=30)
            except subprocess.TimeoutExpired:
                self.proc.kill()
                self.proc.wait(timeout=30)


def spawn_replica(registry_root: str, model: str, *,
                  coordinator: Optional[str] = None,
                  version: str = "latest",
                  n_slots: int = 8, n_blocks: int = 64,
                  block_len: int = 16, steps_per_dispatch: int = 1,
                  warmup_prompt_len: Optional[int] = None,
                  warmup_tokens: int = 2,
                  token: Optional[str] = None,
                  compile_cache_dir: Optional[str] = None,
                  step_floor_ms: Optional[float] = None,
                  ready_timeout_s: float = 300.0) -> ReplicaProcess:
    """Launch one replica worker subprocess serving `model` from the
    on-disk registry; blocks until its READY line (a JSON
    {host, port, token}) arrives. Pass ONE `compile_cache_dir` to every
    replica of a model so warmups after the first replay cached XLA
    compilations instead of re-tracing (`DL4J_COMPILE_CACHE_DIR`)."""
    cmd = [sys.executable, "-m", "deeplearning4j_tpu.serving.replica",
           "--registry", str(registry_root), "--model", str(model),
           "--version", str(version), "--n-slots", str(n_slots),
           "--n-blocks", str(n_blocks), "--block-len", str(block_len),
           "--steps-per-dispatch", str(steps_per_dispatch),
           "--warmup-tokens", str(warmup_tokens)]
    if coordinator is not None:
        cmd += ["--coordinator", coordinator]
    if warmup_prompt_len is not None:
        cmd += ["--warmup-prompt-len", str(warmup_prompt_len)]
    if token is not None:
        cmd += ["--token", token]
    if step_floor_ms is not None:
        cmd += ["--step-floor-ms", str(step_floor_ms)]
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")
    if compile_cache_dir is not None:
        env["DL4J_COMPILE_CACHE_DIR"] = str(compile_cache_dir)
    proc = subprocess.Popen(cmd, stdout=subprocess.PIPE,
                            stderr=sys.stderr, env=env, text=True)
    # readline() has no timeout of its own, and a child hung in model
    # load/warmup prints NOTHING to stdout (its logs go to stderr) —
    # a watchdog kills it at the deadline so the blocked readline
    # returns EOF instead of wedging the caller forever
    timed_out = threading.Event()

    def _watchdog():
        timed_out.set()
        proc.kill()

    watchdog = threading.Timer(ready_timeout_s, _watchdog)
    watchdog.daemon = True
    watchdog.start()
    line = ""
    try:
        while True:
            line = proc.stdout.readline()
            if not line:
                break
            if line.startswith("REPLICA_READY "):
                watchdog.cancel()
                if timed_out.is_set():
                    break        # READY raced the kill: already dead
                info = json.loads(line[len("REPLICA_READY "):])
                return ReplicaProcess(proc, info["host"], info["port"],
                                      info["token"])
        proc.kill()
        if timed_out.is_set():
            raise RuntimeError(
                f"replica subprocess for {model!r} did not report "
                f"ready within {ready_timeout_s}s")
        raise RuntimeError(
            f"replica subprocess for {model!r} never reported ready "
            f"(last line: {line!r})")
    finally:
        watchdog.cancel()


def main(argv=None) -> int:
    import argparse
    p = argparse.ArgumentParser(description="serving replica worker")
    p.add_argument("--registry", required=True)
    p.add_argument("--model", required=True)
    p.add_argument("--version", default="latest")
    p.add_argument("--coordinator", default=None)
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=0)
    p.add_argument("--token", default=None)
    p.add_argument("--n-slots", type=int, default=8)
    p.add_argument("--n-blocks", type=int, default=64)
    p.add_argument("--block-len", type=int, default=16)
    p.add_argument("--steps-per-dispatch", type=int, default=1)
    p.add_argument("--warmup-prompt-len", type=int, default=None)
    p.add_argument("--warmup-tokens", type=int, default=2)
    p.add_argument("--step-floor-ms", type=float, default=None,
                   help="emulated device-step latency floor per decode "
                        "dispatch (sandbox benchmarking seam — see "
                        "GenerationServer.dispatch_floor_s)")
    args = p.parse_args(argv)

    # a serving worker always publishes its gauges: the coordinator
    # federation (heartbeat-piggybacked snapshots) is how the fleet
    # sees per-replica serving_replica_* load
    from deeplearning4j_tpu import monitor
    monitor.enable()

    from deeplearning4j_tpu.serving.registry import ModelRegistry
    version = (args.version if args.version == "latest"
               else int(args.version))
    net, ver = ModelRegistry(args.registry).resolve(args.model, version)
    worker = ReplicaWorker(
        net, model=args.model, version=ver, host=args.host,
        port=args.port, coordinator=args.coordinator, token=args.token,
        warmup_prompt_len=args.warmup_prompt_len,
        warmup_tokens=args.warmup_tokens, n_slots=args.n_slots,
        n_blocks=args.n_blocks, block_len=args.block_len,
        steps_per_dispatch=args.steps_per_dispatch,
        dispatch_floor_s=(None if args.step_floor_ms is None
                          else args.step_floor_ms / 1e3)).start()
    print(f"REPLICA_READY "
          f"{json.dumps(dict(host=worker.host, port=worker.port, token=worker.token))}",
          flush=True)
    try:
        while True:
            time.sleep(1)
    except KeyboardInterrupt:
        pass
    finally:
        worker.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())
