"""Fleet request-plane wire format.

Requests and streamed tokens ride the `streaming/` transports
(`LocalQueueTransport` in-tree, `KafkaTransport` gated on
kafka-python) so clients never hold a server reference — the
decoupling the reference stack got from its Kafka/Camel serving routes
(dl4j-streaming) and TF-Serving got from gRPC. Each message is a JSON
header (routing metadata) followed by the EXISTING ndarray wire bytes
(`streaming.ndarray.serialize_ndarray` — magic, dtype code, dims,
buffer), so the payload half is byte-identical to what every other
route on the transport carries and the transports stay payload-blind.

Topics (one request topic per router, one reply topic per request):

    <prefix>.requests                 client -> router
    <prefix>.replies.<request_id>     router -> client (token chunks)

Frames:

    b"DLFQ" <u32 header_len> <header json> <ND4T prompt bytes>
    b"DLFR" <u32 header_len> <header json> <ND4T token-chunk bytes>

A reply header carries ``seq`` (chunk ordinal), ``done``, the serving
``model``/``version`` tag, and on failure ``error_type``/``error`` —
`decode_reply` re-raises ShedError by name so a shed request fails the
same way remotely as locally.
"""

from __future__ import annotations

import json
import struct
from typing import Optional, Tuple

import numpy as np

from deeplearning4j_tpu.streaming.ndarray import (
    deserialize_ndarray,
    serialize_ndarray,
)

REQUEST_MAGIC = b"DLFQ"
REPLY_MAGIC = b"DLFR"


def _frame(magic: bytes, header: dict, arr: Optional[np.ndarray]) -> bytes:
    hb = json.dumps(header, sort_keys=True).encode()
    payload = b"" if arr is None else serialize_ndarray(np.ascontiguousarray(arr))
    return magic + struct.pack("<I", len(hb)) + hb + payload


def _unframe(magic: bytes, data: bytes) -> Tuple[dict, Optional[np.ndarray]]:
    if data[:4] != magic:
        raise ValueError(
            f"not a {magic.decode()} frame (magic {data[:4]!r})")
    (hlen,) = struct.unpack_from("<I", data, 4)
    header = json.loads(data[8:8 + hlen].decode())
    rest = data[8 + hlen:]
    return header, (deserialize_ndarray(rest) if rest else None)


# ------------------------------------------------------------- requests
def encode_request(model: str, request_id: str, prompt_ids, n_tokens: int,
                   *, temperature: float = 0.0,
                   top_p: Optional[float] = None, rng=None,
                   trace_id: Optional[str] = None) -> bytes:
    """`trace_id` is the distributed-tracing context field: a client-
    minted id the router rehydrates into a `RequestTrace`, so the
    remote request's server-side spans land on the SAME timeline as the
    client's (one stitched trace per request across the wire)."""
    header = {
        "model": str(model),
        "request_id": str(request_id),
        "n_tokens": int(n_tokens),
        "temperature": float(temperature),
        "top_p": None if top_p is None else float(top_p),
        "rng": None if rng is None else
               [int(x) for x in np.asarray(rng, np.uint32).reshape(2)],
    }
    if trace_id is not None:
        header["trace_id"] = str(trace_id)
    return _frame(REQUEST_MAGIC, header,
                  np.asarray(prompt_ids, np.int64))


def decode_request(data: bytes) -> Tuple[dict, np.ndarray]:
    """(header, prompt_ids). Raises ValueError on a non-request frame."""
    header, prompt = _unframe(REQUEST_MAGIC, data)
    if prompt is None:
        raise ValueError("request frame carries no prompt payload")
    if header.get("rng") is not None:
        header["rng"] = np.asarray(header["rng"], np.uint32)
    return header, prompt


# --------------------------------------------------------------- replies
def encode_reply(request_id: str, seq: int, tokens, *, done: bool,
                 model: Optional[str] = None,
                 version: Optional[int] = None,
                 error: Optional[BaseException] = None) -> bytes:
    header = {
        "request_id": str(request_id),
        "seq": int(seq),
        "done": bool(done),
        "model": model,
        "version": version,
    }
    if error is not None:
        header["error_type"] = type(error).__name__
        header["error"] = str(error)
    toks = np.asarray([] if tokens is None else tokens, np.int32)
    return _frame(REPLY_MAGIC, header, toks)


def decode_reply(data: bytes) -> Tuple[dict, np.ndarray]:
    """(header, token_chunk). The header's error fields are left to the
    caller (`RemoteTokenStream` maps error_type == "ShedError" back to
    ShedError, everything else to RuntimeError)."""
    header, toks = _unframe(REPLY_MAGIC, data)
    return header, (np.zeros(0, np.int32) if toks is None
                    else toks.astype(np.int32))


def reply_error(header: dict) -> Optional[BaseException]:
    """Rehydrate a reply header's error, preserving the shed/failure
    distinction across the wire."""
    if "error_type" not in header:
        return None
    from deeplearning4j_tpu.serving.server import ShedError
    msg = f"{header.get('error', '')} (remote {header['error_type']})"
    if header["error_type"] == "ShedError":
        return ShedError(msg)
    return RuntimeError(msg)
