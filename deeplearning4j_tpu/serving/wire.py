"""Fleet request-plane wire format.

Requests and streamed tokens ride the `streaming/` transports
(`LocalQueueTransport` in-tree, `KafkaTransport` gated on
kafka-python) so clients never hold a server reference — the
decoupling the reference stack got from its Kafka/Camel serving routes
(dl4j-streaming) and TF-Serving got from gRPC. Each message is a JSON
header (routing metadata) followed by the EXISTING ndarray wire bytes
(`streaming.ndarray.serialize_ndarray` — magic, dtype code, dims,
buffer), so the payload half is byte-identical to what every other
route on the transport carries and the transports stay payload-blind.

Topics (one request topic per router, one reply topic per request):

    <prefix>.requests                 client -> router
    <prefix>.replies.<request_id>     router -> client (token chunks)

Frames:

    b"DLFQ" <u32 header_len> <header json> <ND4T prompt bytes>
    b"DLFR" <u32 header_len> <header json> <ND4T token-chunk bytes>
    b"DLFP" <u32 header_len> <header json> <ND4T stacked K/V bytes>

A reply header carries ``seq`` (chunk ordinal), ``done``, the serving
``model``/``version`` tag, and on failure ``error_type``/``error`` —
`decode_reply` re-raises ShedError by name so a shed request fails the
same way remotely as locally.

The PFD (prefill→decode) frame is the disaggregation handoff: the
header is the slot's host state (request id, positions, sampling
params, emitted history), the payload the granted K/V blocks gathered
from the paged pool and stacked ``[n_layers, 2, n_blocks, block_len,
heads, head_dim]`` in the pool's compute dtype, so a decode worker can
adopt the slot bit-identically to the colocated path.

Every decoder in this module raises `WireFormatError` on truncated or
corrupted bytes and on unknown magics — `struct.error`/`KeyError`/
json decoding errors never leak to callers, so a transport delivering
garbage degrades to one typed, catchable failure.

Replica sockets carry these frames length-prefixed (`send_frame`/
`recv_frame`): ``<u32 frame_len> <frame bytes>`` per message, since
TCP gives a byte stream, not message boundaries.
"""

from __future__ import annotations

import json
import struct
from typing import Optional, Tuple

import numpy as np

from deeplearning4j_tpu.streaming.ndarray import (
    deserialize_ndarray,
    serialize_ndarray,
)

REQUEST_MAGIC = b"DLFQ"
REPLY_MAGIC = b"DLFR"
HANDOFF_MAGIC = b"DLFP"

KNOWN_MAGICS = (REQUEST_MAGIC, REPLY_MAGIC, HANDOFF_MAGIC)

# largest frame a socket peer will accept: the K/V handoff for a real
# request is tens of MB at sandbox shapes; 1 GiB bounds a hostile or
# corrupted length prefix without constraining any legitimate frame
MAX_FRAME_BYTES = 1 << 30


class WireFormatError(ValueError):
    """A frame failed to decode: truncated bytes, an unknown or
    mismatched magic, malformed header JSON, or a corrupt ndarray
    payload. Subclasses ValueError so pre-existing `except ValueError`
    call sites keep working."""


def _frame(magic: bytes, header: dict, arr: Optional[np.ndarray]) -> bytes:
    hb = json.dumps(header, sort_keys=True).encode()
    payload = b"" if arr is None else serialize_ndarray(np.ascontiguousarray(arr))
    return magic + struct.pack("<I", len(hb)) + hb + payload


def _unframe(magic: bytes, data: bytes) -> Tuple[dict, Optional[np.ndarray]]:
    if not isinstance(data, (bytes, bytearray, memoryview)):
        raise WireFormatError(
            f"frame must be bytes, got {type(data).__name__}")
    data = bytes(data)
    if len(data) < 8:
        raise WireFormatError(
            f"truncated frame: {len(data)} bytes, need at least 8")
    if data[:4] != magic:
        got = data[:4]
        if got in KNOWN_MAGICS:
            raise WireFormatError(
                f"not a {magic.decode()} frame (got {got.decode()})")
        raise WireFormatError(
            f"not a {magic.decode()} frame (unknown magic {got!r})")
    (hlen,) = struct.unpack_from("<I", data, 4)
    if 8 + hlen > len(data):
        raise WireFormatError(
            f"truncated frame: header claims {hlen} bytes but only "
            f"{len(data) - 8} follow the magic")
    try:
        header = json.loads(data[8:8 + hlen].decode())
    except (UnicodeDecodeError, json.JSONDecodeError) as e:
        raise WireFormatError(f"malformed frame header: {e}") from e
    if not isinstance(header, dict):
        raise WireFormatError(
            f"frame header must be a JSON object, got "
            f"{type(header).__name__}")
    rest = data[8 + hlen:]
    if not rest:
        return header, None
    try:
        return header, deserialize_ndarray(rest)
    except (ValueError, struct.error, TypeError) as e:
        raise WireFormatError(f"corrupt ndarray payload: {e}") from e


def _require(header: dict, keys, magic: bytes) -> None:
    missing = [k for k in keys if k not in header]
    if missing:
        raise WireFormatError(
            f"{magic.decode()} header missing field(s) {missing}")


# ------------------------------------------------------------- requests
def encode_request(model: str, request_id: str, prompt_ids, n_tokens: int,
                   *, temperature: float = 0.0,
                   top_p: Optional[float] = None, rng=None,
                   emit_start: int = 0,
                   trace_id: Optional[str] = None) -> bytes:
    """`trace_id` is the distributed-tracing context field: a client-
    minted id the router rehydrates into a `RequestTrace`, so the
    remote request's server-side spans land on the SAME timeline as the
    client's (one stitched trace per request across the wire).

    `emit_start` is the migration continuation seam: a stream that died
    on one replica after K tokens resubmits to another as
    prompt+received with ``emit_start=K``, preserving the sampled
    fold_in(key, position) chain (zero is omitted from the header —
    pre-migration peers decode these frames unchanged)."""
    header = {
        "model": str(model),
        "request_id": str(request_id),
        "n_tokens": int(n_tokens),
        "temperature": float(temperature),
        "top_p": None if top_p is None else float(top_p),
        "rng": None if rng is None else
               [int(x) for x in np.asarray(rng, np.uint32).reshape(2)],
    }
    if emit_start:
        header["emit_start"] = int(emit_start)
    if trace_id is not None:
        header["trace_id"] = str(trace_id)
    return _frame(REQUEST_MAGIC, header,
                  np.asarray(prompt_ids, np.int64))


def decode_request(data: bytes) -> Tuple[dict, np.ndarray]:
    """(header, prompt_ids). Raises WireFormatError on a non-request
    or corrupt frame."""
    header, prompt = _unframe(REQUEST_MAGIC, data)
    if prompt is None:
        raise WireFormatError("request frame carries no prompt payload")
    _require(header, ("model", "request_id", "n_tokens"), REQUEST_MAGIC)
    if header.get("rng") is not None:
        try:
            header["rng"] = np.asarray(header["rng"], np.uint32)
        except (ValueError, TypeError) as e:
            raise WireFormatError(f"malformed rng field: {e}") from e
    return header, prompt


# --------------------------------------------------------------- replies
def encode_reply(request_id: str, seq: int, tokens, *, done: bool,
                 model: Optional[str] = None,
                 version: Optional[int] = None,
                 error: Optional[BaseException] = None) -> bytes:
    header = {
        "request_id": str(request_id),
        "seq": int(seq),
        "done": bool(done),
        "model": model,
        "version": version,
    }
    if error is not None:
        header["error_type"] = type(error).__name__
        header["error"] = str(error)
    toks = np.asarray([] if tokens is None else tokens, np.int32)
    return _frame(REPLY_MAGIC, header, toks)


def decode_reply(data: bytes) -> Tuple[dict, np.ndarray]:
    """(header, token_chunk). The header's error fields are left to the
    caller (`RemoteTokenStream` maps error_type == "ShedError" back to
    ShedError, everything else to RuntimeError)."""
    header, toks = _unframe(REPLY_MAGIC, data)
    _require(header, ("request_id", "seq", "done"), REPLY_MAGIC)
    return header, (np.zeros(0, np.int32) if toks is None
                    else toks.astype(np.int32))


def reply_error(header: dict) -> Optional[BaseException]:
    """Rehydrate a reply header's error, preserving the shed/failure
    distinction across the wire."""
    if "error_type" not in header:
        return None
    from deeplearning4j_tpu.serving.server import ShedError
    msg = f"{header.get('error', '')} (remote {header['error_type']})"
    if header["error_type"] == "ShedError":
        return ShedError(msg)
    return RuntimeError(msg)


# ------------------------------------------------- PFD handoff frames
# Fields every handoff header must carry for a decode worker to rebuild
# the slot's host state exactly (see PagedDecodeEngine.export_handoff).
HANDOFF_FIELDS = ("request_id", "prompt_len", "n_tokens", "pos",
                  "remaining", "emit_base", "emitted", "last_token",
                  "history", "keys", "temperature", "block_len")


def encode_handoff(header: dict, kv: np.ndarray) -> bytes:
    """PFD frame: `header` is the slot-state dict the engine exports,
    `kv` the stacked per-layer K/V blocks
    ``[n_layers, 2, n_blocks, block_len, heads, head_dim]``."""
    _require(header, HANDOFF_FIELDS, HANDOFF_MAGIC)
    return _frame(HANDOFF_MAGIC, header, np.ascontiguousarray(kv))


def decode_handoff(data: bytes) -> Tuple[dict, np.ndarray]:
    """(header, kv). Raises WireFormatError on a non-handoff or
    corrupt frame, including a payload whose shape cannot be a stacked
    K/V block set."""
    header, kv = _unframe(HANDOFF_MAGIC, data)
    _require(header, HANDOFF_FIELDS, HANDOFF_MAGIC)
    if kv is None or kv.ndim != 6 or kv.shape[1] != 2:
        shape = None if kv is None else kv.shape
        raise WireFormatError(
            f"handoff payload is not stacked K/V blocks "
            f"[L, 2, B, block_len, H, Dh] (shape {shape})")
    if kv.shape[3] != int(header["block_len"]):
        raise WireFormatError(
            f"handoff payload block_len {kv.shape[3]} != header "
            f"block_len {header['block_len']}")
    return header, kv


# ------------------------------------------- socket framing (replicas)
def send_frame(sock, frame: bytes) -> None:
    """Write one length-prefixed frame to a connected socket."""
    if len(frame) > MAX_FRAME_BYTES:
        raise WireFormatError(
            f"frame of {len(frame)} bytes exceeds the "
            f"{MAX_FRAME_BYTES}-byte wire bound")
    sock.sendall(struct.pack("<I", len(frame)) + frame)


def _recv_exact(sock, n: int) -> bytes:
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(min(1 << 20, n - len(buf)))
        if not chunk:
            raise ConnectionError(
                f"peer closed mid-frame ({len(buf)}/{n} bytes)")
        buf += chunk
    return bytes(buf)


def recv_frame(sock) -> bytes:
    """Read one length-prefixed frame. Raises ConnectionError on a
    clean or mid-frame close, WireFormatError on an absurd length
    prefix (corrupt stream)."""
    prefix = bytearray()
    while len(prefix) < 4:
        chunk = sock.recv(4 - len(prefix))
        if not chunk:
            if prefix:
                raise ConnectionError(
                    "peer closed mid-frame (inside length prefix)")
            raise ConnectionError("peer closed the connection")
        prefix += chunk
    (n,) = struct.unpack("<I", bytes(prefix))
    if n > MAX_FRAME_BYTES:
        raise WireFormatError(
            f"length prefix {n} exceeds the {MAX_FRAME_BYTES}-byte "
            f"wire bound (corrupt stream?)")
    return _recv_exact(sock, n)
