"""FleetServer — multi-model hosting with zero-downtime hot-swap.

One `GenerationServer` over one hard-wired model is a demo; the fleet
tier hosts N named models resolved from a `ModelRegistry` and replaces
any of them under live traffic. The swap discipline is the TPU-fleet
retrospective's (arXiv:2606.15870) drain protocol applied to serving:

1. **Warm the successor first.** The new version's server runs the
   FULL `warmup()` grid (every wave width x length bucket x program
   variant) while the incumbent still takes traffic — post-swap
   admissions must show no compile cliff (p50==p99 TTFT collapse was
   the measured cost of compiling inside a live wave).
2. **Flip the pointer.** `active(name)` atomically returns the
   successor; every new submit lands there. The `FleetRouter` retries
   a submit that raced the flip, so no request falls into the gap.
3. **Drain the incumbent.** `GenerationServer.drain()` closes its
   admissions and waits for every already-admitted stream — which
   finish ON THE OLD WEIGHTS (version-tagged greedy parity: an
   in-flight v stream completes bit-equal to an unswapped v
   reference). Zero streams dropped, zero streams reset.
4. **Stop + unpin.** Only a fully-drained incumbent is stopped; its
   registry pin lifts so retention may collect the old version.

`scale()` is the same machinery with the SAME version: a warmed
successor with more slots / a bigger pool replaces the incumbent with
zero dropped streams — which is what makes slot-count/pool-size
autoscaling (`FleetAutoscaler`, reading the queue-depth and
`*_pool_blocks_*` gauges) safe to fire under load.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Dict, List, Optional, Tuple

from deeplearning4j_tpu.monitor.flightrec import GLOBAL_FLIGHT_RECORDER
from deeplearning4j_tpu.serving.registry import ModelRegistry
from deeplearning4j_tpu.serving.server import GenerationServer

log = logging.getLogger("deeplearning4j_tpu.serving.fleet")


class _Deployment:
    __slots__ = ("name", "version", "server", "server_kw", "warm_len",
                 "warm_tokens")

    def __init__(self, name, version, server, server_kw, warm_len,
                 warm_tokens):
        self.name = name
        self.version = version
        self.server = server
        self.server_kw = server_kw
        self.warm_len = warm_len
        self.warm_tokens = warm_tokens


class FleetServer:
    """N named models from a registry, each behind its own
    `GenerationServer`, swappable under live traffic."""

    def __init__(self, registry: ModelRegistry, *,
                 gauge_interval_s: float = 0.25):
        self.registry = registry
        self.gauge_interval_s = float(gauge_interval_s)
        self._models: Dict[str, _Deployment] = {}
        self._deploying: set = set()
        # incumbents whose swap-time drain TIMED OUT: still running
        # with admissions closed (never stopped — that would drop
        # streams). Kept addressable here so `reap_retired()` can
        # finish the job once their streams end; swap() reaps at entry.
        self._retired: List[Tuple[str, int, GenerationServer]] = []
        # model names whose gauges were published at least once — how
        # publish_gauges knows which retired names still need their
        # families zeroed (a popped deployment otherwise keeps
        # exporting its last live-looking values forever)
        self._gauged: set = set()
        self._lock = threading.Lock()
        # shared-prefix registrations per model NAME: re-applied to
        # every successor a swap/scale builds, so a warmed system
        # prompt survives version flips — each application prefills
        # under the SUCCESSOR's weights, which is what keys the cache
        # on (token ids, model version) by construction
        self._prefixes: Dict[str, List] = {}
        # one RLock per model name serializing the whole
        # build→flip→drain sequence: a version swap racing an
        # autoscale resize would otherwise both replace the same
        # incumbent and leak whichever successor lost the pointer race
        # (never drained, never stopped, pin never released)
        self._swap_locks: Dict[str, threading.RLock] = {}
        self._metrics_cache = None
        self._gauge_thread: Optional[threading.Thread] = None
        self._gauge_running = False

    # ------------------------------------------------------------ queries
    def has(self, name: str) -> bool:
        with self._lock:
            return name in self._models

    def names(self) -> List[str]:
        with self._lock:
            return sorted(self._models)

    def active(self, name: str) -> Tuple[GenerationServer, int]:
        """(server, version) currently serving `name` — ONE atomic read
        of the swap pointer (the router's resolve primitive; reading
        server and version separately could straddle a flip and
        mis-tag a stream's version)."""
        with self._lock:
            d = self._models.get(name)
            if d is None:
                raise KeyError(f"no deployed model {name!r} "
                               f"(deployed: {sorted(self._models)})")
            return d.server, d.version

    def server(self, name: str) -> GenerationServer:
        return self.active(name)[0]

    def version(self, name: str) -> int:
        return self.active(name)[1]

    # ------------------------------------------------------------ metrics
    def _metrics(self):
        from deeplearning4j_tpu import monitor
        return monitor.resolve_cached_metrics(
            self, "_metrics_cache", self._build_metrics)

    @staticmethod
    def _build_metrics(reg):
        def g(fam, help_):
            return lambda name: reg.gauge(fam, help_, model=name)

        return {
            "active_models": reg.gauge(
                "fleet_active_models", "models the fleet is serving"),
            "version": g("fleet_model_version",
                         "registry version currently served"),
            "queue": g("fleet_queue_depth",
                       "requests awaiting admission per model"),
            "slots_active": g("fleet_active_slots",
                              "slots decoding right now per model"),
            "slots": g("fleet_slot_count",
                       "configured serving slots per model (the "
                       "autoscaler's lever)"),
            "pool_free": g("fleet_pool_blocks_free",
                           "free KV-pool blocks per model"),
            "pool_used": g("fleet_pool_blocks_used",
                           "granted KV-pool blocks per model"),
            "open": g("fleet_open_streams",
                      "streams submitted and unfinished per model"),
            "swaps": lambda name: reg.counter(
                "fleet_swaps_total",
                "zero-downtime server replacements (version swaps + "
                "autoscale resizes)", model=name),
        }

    def publish_gauges(self):
        """Push every deployment's live state onto the per-model
        `fleet_*` gauge families — the /serving page's and the
        autoscaler's signal plane. Gauges of UNDEPLOYED models are
        zeroed (version=0 marks the row retired; the /serving page and
        the autoscaler skip those) — the registry has no
        family-child removal, and stale live-looking values would show
        a retired model as still serving."""
        m = self._metrics()
        if m is None:
            return
        with self._lock:
            deployments = list(self._models.values())
            gauged = set(self._gauged)
            self._gauged.update(d.name for d in deployments)
        m["active_models"].set(len(deployments))
        live = set()
        for d in deployments:
            live.add(d.name)
            s = d.server
            m["version"](d.name).set(d.version)
            m["queue"](d.name).set(s.queue_depth())
            m["slots_active"](d.name).set(s.engine.active_slots)
            m["slots"](d.name).set(s.engine.n_slots)
            m["pool_free"](d.name).set(s.engine.pool.free_blocks)
            m["pool_used"](d.name).set(s.engine.pool.used_blocks)
            m["open"](d.name).set(s.open_streams)
        retired = gauged - live
        for name in retired:
            for fam in ("version", "queue", "slots_active", "slots",
                        "pool_free", "pool_used", "open"):
                m[fam](name).set(0)
        if retired:
            with self._lock:
                self._gauged.difference_update(retired)

    def _gauge_loop(self):
        while self._gauge_running:
            try:
                self.publish_gauges()
            except Exception:  # noqa: BLE001 — telemetry must not kill serving
                log.exception("fleet gauge publish failed (continuing)")
            time.sleep(self.gauge_interval_s)

    def _ensure_gauge_thread(self):
        if self._gauge_thread is None:
            self._gauge_running = True
            self._gauge_thread = threading.Thread(target=self._gauge_loop,
                                                  daemon=True)
            self._gauge_thread.start()

    # ------------------------------------------------------------- deploy
    def _release_version(self, name: str, version: int):
        """Release the retention pin of a no-longer-served version —
        the ONE seam swap()/undeploy()/reap_retired() go through, so a
        subclass whose versions live in a different store (the
        TenantFleet's per-tenant adapter sequence) redirects every
        release by overriding this."""
        self.registry.unpin(name, version)

    def _build_server(self, name: str, version, server_kw: dict,
                      warm_len: Optional[int], warm_tokens: int):
        """Resolve + warm + start one server. The target version is
        PINNED BEFORE resolve: retention GC on a concurrent publish
        must never collect the zip of a version being (or about to be)
        served — resolve-then-pin left a GC window as wide as the
        whole warmup. Pins taken here are released on failure (but
        never a pin some live deployment already held)."""
        reg = self.registry
        target = (reg.latest(name) if version == "latest"
                  else int(version))
        if target is None:
            raise FileNotFoundError(
                f"no published versions of {name!r} in the registry")
        pinned_here = []

        def pin(v):
            if (name, v) not in reg.pinned():
                reg.pin(name, v)
                pinned_here.append(v)

        pin(target)
        try:
            net, v = reg.resolve(name, version)
            if v != target:
                # "latest" fell back past a corrupt newest: keep the
                # version actually loaded, release the target pin
                pin(v)
                if target in pinned_here:
                    reg.unpin(name, target)
                    pinned_here.remove(target)
            # label the server's serving_* metric families: two fleet
            # deployments share one process registry and must not
            # collide on unlabeled series (callers may still override)
            server_kw = dict(server_kw)
            server_kw.setdefault("name", name)
            server = GenerationServer(net, **server_kw)
            # shared prefixes registered for this NAME re-apply to the
            # successor BEFORE warmup (prefill under the new weights;
            # warmup then pre-compiles the suffix-extension programs).
            # The radix prefix cache needs NO such replay: the
            # `prefix_cache="radix"` kwarg rides server_kw through
            # swap()/scale(), and the successor's tree rebuilds itself
            # from live traffic — every admission inserts its prompt
            # blocks, so dedup resumes within one wave of repeats and
            # stale-weight K/V can never leak across a swap
            with self._lock:
                prefixes = list(self._prefixes.get(name, ()))
            for ids in prefixes:
                server.register_prefix(ids)
            if warm_len is not None:
                # the FULL (width x bucket x variant) grid — compiling
                # inside a live admission wave is the p99 cliff the
                # swap contract forbids
                server.warmup(int(warm_len), warm_tokens)
            server.start()
            return server, v
        except Exception:
            for v_ in pinned_here:
                reg.unpin(name, v_)
            raise

    def deploy(self, name: str, version="latest", *,
               warmup_prompt_len: Optional[int] = None,
               warmup_tokens: int = 2, **server_kw) -> int:
        """Resolve `name`@`version` from the registry, warm a server
        (skipped when `warmup_prompt_len` is None — tests), start it,
        and pin the served version against retention GC. Returns the
        version deployed. Re-deploying a live name is a `swap()`."""
        # check-and-RESERVE under the lock: two concurrent deploys of
        # one name both passing an unlocked has() check would each
        # build a warmed server and the overwritten one would leak
        # started, pinned and undrained forever
        with self._lock:
            if name in self._models or name in self._deploying:
                raise ValueError(f"{name!r} is already deployed — use "
                                 f"swap() to replace it under traffic")
            self._deploying.add(name)
        try:
            server, v = self._build_server(name, version, server_kw,
                                           warmup_prompt_len,
                                           warmup_tokens)
            with self._lock:
                self._models[name] = _Deployment(
                    name, v, server, dict(server_kw), warmup_prompt_len,
                    warmup_tokens)
                self._swap_locks.setdefault(name, threading.RLock())
            # registrations that raced the build (after _build_server's
            # prefix snapshot, before the swap lock existed) re-apply
            # idempotently now that the deployment is addressable
            with self._lock:
                missed = list(self._prefixes.get(name, ()))
            for ids in missed:
                server.register_prefix(ids)
        finally:
            with self._lock:
                self._deploying.discard(name)
        self._ensure_gauge_thread()
        self.publish_gauges()
        GLOBAL_FLIGHT_RECORDER.record("deploy", model=name, version=v)
        log.info("deployed %s v%d", name, v)
        return v

    def register_prefix(self, name: str, token_ids) -> tuple:
        """Register a shared prompt prefix for model `name`: the
        ACTIVE server warms it now (copy-on-write block reuse,
        `GenerationServer.register_prefix`), and every successor a
        later `swap()`/`scale()` builds re-registers it automatically
        — re-prefilled under the successor's weights, so the cache is
        effectively keyed on (token ids, model version). Registration
        is remembered even for a not-yet-deployed name (applied at
        deploy)."""
        import numpy as np

        ids = np.asarray(token_ids)
        if ids.ndim == 2 and ids.shape[0] == 1:
            ids = ids[0]
        with self._lock:
            known = self._prefixes.setdefault(name, [])
            if not any(np.array_equal(ids, k) for k in known):
                known.append(ids)
            swap_lock = self._swap_locks.get(name)
        # serialize against swap()/scale(): a registration racing a
        # mid-build swap would otherwise apply only to the RETIRING
        # incumbent (the successor snapshotted _prefixes before this
        # entry landed) and the successor would silently serve without
        # it — waiting out the swap applies it to the live winner
        if swap_lock is not None:
            with swap_lock:
                with self._lock:
                    d = self._models.get(name)
                if d is not None:
                    return d.server.register_prefix(ids)
        return tuple(int(t) for t in ids)

    # --------------------------------------------------------------- swap
    def swap(self, name: str, version="latest", *,
             drain_timeout: float = 600.0, **server_overrides) -> int:
        """Zero-downtime replacement: warm the successor FULLY, flip
        the active pointer, drain the incumbent (its in-flight streams
        finish on the old weights), stop it, unpin the old version.
        Raises on drain timeout WITHOUT stopping the incumbent — a
        timeout must not convert into dropped streams.

        Swaps of the same name are SERIALIZED (per-name RLock): a
        version swap racing an autoscale resize must not both replace
        one incumbent — the losing successor would leak warmed,
        running and pinned forever."""
        self.reap_retired()      # finish any drain-timeout leftovers
        with self._lock:
            swap_lock = self._swap_locks.get(name)
        if swap_lock is None:
            raise KeyError(f"no deployed model {name!r} to swap")
        with swap_lock:
            with self._lock:
                d = self._models.get(name)
                if d is None:
                    raise KeyError(f"no deployed model {name!r} to swap")
                old_server, old_version = d.server, d.version
                kw = {**d.server_kw, **server_overrides}
                warm_len, warm_tokens = d.warm_len, d.warm_tokens
            successor, v = self._build_server(name, version, kw,
                                              warm_len, warm_tokens)
            with self._lock:
                d = self._models[name]
                d.server, d.version, d.server_kw = successor, v, kw
            # queued-but-unstarted requests MIGRATE to the warmed
            # successor instead of waiting out the incumbent's drain
            # behind its in-flight streams: a queued request has
            # emitted nothing, so it has no old-weights state to honor
            # — it moves wholesale (same TokenStream, same consumer
            # future) and decodes entirely on the successor. In-flight
            # streams stay put and finish on the old weights (the
            # version-parity contract).
            moved = old_server.export_queued()
            if moved:
                try:
                    successor.adopt_queued(moved)
                    # a migrated request decodes ENTIRELY on the
                    # successor, so the router's version tag must
                    # follow it — keeping the incumbent's version on
                    # the stream would break version-tagged parity
                    for item in moved:
                        st = item[0].stream
                        if getattr(st, "version", None) is not None:
                            st.version = v
                    GLOBAL_FLIGHT_RECORDER.record(
                        "swap_migrate", model=name, count=len(moved),
                        to_version=v)
                except Exception:  # noqa: BLE001 — a refusing successor
                    # must not lose the requests: put them back on the
                    # incumbent (drain below then serves them out)
                    log.exception("swap migration refused; requests "
                                  "stay on the incumbent")
                    old_server.adopt_queued(moved)
            # from here every router resolve sees the successor; the
            # incumbent only owes its already-admitted streams
            drained = old_server.drain(timeout=drain_timeout)
            if not drained:
                # keep the incumbent ADDRESSABLE: it is no longer in
                # _models (the successor is), and without this record
                # no fleet API could ever stop it or release its pin
                with self._lock:
                    self._retired.append((name, old_version,
                                          old_server))
                GLOBAL_FLIGHT_RECORDER.record(
                    "drain_timeout", model=name, version=old_version,
                    timeout_s=drain_timeout,
                    open_streams=old_server.open_streams)
                raise RuntimeError(
                    f"{name!r} incumbent (v{old_version}) did not drain "
                    f"within {drain_timeout}s — it is left running "
                    f"(admissions closed) so no stream is dropped; "
                    f"call reap_retired() once its streams finish")
            old_server.stop()
            if old_version != v:
                self._release_version(name, old_version)
        m = self._metrics()
        if m is not None:
            m["swaps"](name).inc()
        GLOBAL_FLIGHT_RECORDER.record(
            "swap", model=name, from_version=old_version, to_version=v)
        self.publish_gauges()
        log.info("swapped %s v%d -> v%d (drained clean)", name,
                 old_version, v)
        return v

    def scale(self, name: str, *, n_slots: Optional[int] = None,
              n_blocks: Optional[int] = None,
              drain_timeout: float = 600.0) -> dict:
        """Resize a deployment's serving capacity with the swap
        machinery at the SAME registry version (same weights — every
        stream keeps greedy parity across the resize). Holds the
        per-name swap lock across read-current-version + swap, so a
        concurrent version swap can't interleave and get reverted."""
        with self._lock:
            swap_lock = self._swap_locks.get(name)
        if swap_lock is None:
            raise KeyError(f"no deployed model {name!r} to scale")
        with swap_lock:             # RLock: the nested swap() re-enters
            with self._lock:
                d = self._models.get(name)
                if d is None:
                    raise KeyError(
                        f"no deployed model {name!r} to scale")
                before = {"n_slots": d.server.engine.n_slots,
                          "n_blocks": d.server.engine.pool.n_blocks}
                version = d.version
            overrides = {}
            if n_slots is not None:
                overrides["n_slots"] = int(n_slots)
            if n_blocks is not None:
                overrides["n_blocks"] = int(n_blocks)
            if not overrides:
                raise ValueError("scale() needs n_slots and/or n_blocks")
            self.swap(name, version=version,
                      drain_timeout=drain_timeout, **overrides)
            after = {"n_slots": self.server(name).engine.n_slots,
                     "n_blocks": self.server(name).engine.pool.n_blocks}
        GLOBAL_FLIGHT_RECORDER.record(
            "scale", model=name, version=version, before=before,
            after=after)
        return {"name": name, "version": version, "before": before,
                "after": after}

    # ------------------------------------------------------------ teardown
    def reap_retired(self, *, force: bool = False) -> int:
        """Finish off incumbents whose swap-time drain timed out: stop
        (and unpin) every retired server whose streams have since
        ended — or all of them with `force=True` (failing whatever is
        still in flight). Returns the number reaped. swap() calls this
        at entry, so a later swap on the same name cleans up its
        predecessor automatically."""
        with self._lock:
            retired, self._retired = self._retired, []
            live = {(d.name, d.version)
                    for d in self._models.values()}
        reaped, kept = 0, []
        for name, version, server in retired:
            if force or server.open_streams == 0:
                server.stop()
                # a same-version rescale's retiree shares its pin with
                # the LIVE deployment — never release a pin a live
                # server still needs
                if (name, version) not in live:
                    self._release_version(name, version)
                GLOBAL_FLIGHT_RECORDER.record(
                    "reap_retired", model=name, version=version,
                    forced=bool(force))
                reaped += 1
            else:
                kept.append((name, version, server))
        if kept:
            with self._lock:
                self._retired.extend(kept)
        return reaped

    def undeploy(self, name: str, *, drain: bool = True,
                 drain_timeout: float = 600.0):
        """Retire a deployment. Serialized with swap()/scale() via the
        per-name lock (an undeploy racing a mid-warmup swap would let
        the swap crash after building a successor that then leaks
        started and pinned). With `drain=True` a drain TIMEOUT raises
        and leaves the server deployed with admissions closed — the
        swap rule: a timeout must not convert into dropped streams.
        `drain=False` is the explicit force path (in-flight streams
        fail)."""
        with self._lock:
            swap_lock = self._swap_locks.get(name)
        if swap_lock is None:
            raise KeyError(f"no deployed model {name!r}")
        with swap_lock:
            with self._lock:
                d = self._models.get(name)
                if d is None:
                    raise KeyError(f"no deployed model {name!r}")
            if drain and not d.server.drain(timeout=drain_timeout):
                raise RuntimeError(
                    f"{name!r} did not drain within {drain_timeout}s — "
                    f"still deployed with admissions closed so no "
                    f"stream is dropped; retry once its streams finish "
                    f"(or undeploy(drain=False) to force)")
            with self._lock:
                self._models.pop(name, None)
            d.server.stop()
            self._release_version(name, d.version)
        GLOBAL_FLIGHT_RECORDER.record(
            "undeploy", model=name, version=d.version,
            drained=bool(drain))
        self.publish_gauges()

    def stop(self, *, drain: bool = False,
             drain_timeout: float = 600.0):
        """Stop every deployment (drain first when asked) and the
        gauge publisher. Idempotent. Each undeploy takes the per-name
        swap lock, so an in-progress swap finishes before its model is
        retired; with `drain=True`, models whose drain times out are
        LEFT RUNNING (admissions closed) and reported in one raised
        error after the rest have stopped."""
        stuck = []
        for name in self.names():
            try:
                self.undeploy(name, drain=drain,
                              drain_timeout=drain_timeout)
            except KeyError:
                pass            # undeployed concurrently
            except RuntimeError as e:
                stuck.append(str(e))
        # drain-timeout leftovers from earlier swaps: force semantics
        # match stop(drain=False); with drain=True they are only
        # reaped once their streams ended
        self.reap_retired(force=not drain)
        self._gauge_running = False
        if self._gauge_thread is not None:
            self._gauge_thread.join(timeout=10)
            self._gauge_thread = None
        self.publish_gauges()
        if stuck:
            raise RuntimeError("fleet stop left models draining: "
                               + "; ".join(stuck))

    def __enter__(self):
        return self

    def __exit__(self, *a):
        self.stop()


class FleetAutoscaler:
    """Gauge-driven capacity scaling: when a model's admission queue
    backs up or its KV pool runs low on free blocks, replace its server
    with a bigger one (`FleetServer.scale` — a warmed swap, so the
    resize drops zero streams).

    The decision inputs are the per-model `fleet_queue_depth` /
    `fleet_pool_blocks_{free,used}` gauge families on the metrics
    registry — the SAME signal plane /metrics exports (the gauges the
    ROADMAP names as the autoscaling inputs) — with a live-state
    fallback when monitoring is disabled.

    Rules-driven mode: pass `rules=` (an `monitor.alerts.AlertEngine`)
    and the pressure derivation flips from the two hardcoded thresholds
    to the declarative rule set — a FIRING alert is pressure for the
    model its rule's `model=`/`server=` label names (fleet-wide when
    unlabeled), and `goodput_low=` adds a `serving_goodput_fraction`
    floor (scale out when device work stops turning into kept tokens).
    The legacy thresholds remain the default.

    Horizontal mode: pass `replicas=` (a `serving.replica.
    ReplicaManager`) and the SAME pressure signal gains a second axis —
    when a model is under pressure but its vertical levers are at their
    caps (`max_slots`/`max_blocks`), the autoscaler GROWS the replica
    count instead (decision records carry ``action:
    "grow_replicas"``); after `replica_idle_passes` consecutive
    pressure-free passes with an empty queue it SHRINKS back toward
    `ReplicaManager.min_replicas` (newest replica first)."""

    def __init__(self, fleet: FleetServer, *,
                 queue_depth_high: int = 32,
                 pool_free_frac_low: float = 0.25,
                 factor: int = 2, max_slots: int = 64,
                 max_blocks: int = 8192, cooldown_s: float = 0.0,
                 drain_timeout: float = 600.0,
                 rules=None, goodput_low: Optional[float] = None,
                 replicas=None, replica_idle_passes: int = 4):
        self.fleet = fleet
        self.queue_depth_high = int(queue_depth_high)
        self.pool_free_frac_low = float(pool_free_frac_low)
        self.factor = int(factor)
        self.max_slots = int(max_slots)
        self.max_blocks = int(max_blocks)
        self.cooldown_s = float(cooldown_s)
        self.drain_timeout = float(drain_timeout)
        self.rules = rules
        self.goodput_low = (None if goodput_low is None
                            else float(goodput_low))
        # horizontal axis: a ReplicaManager (or anything with
        # count/grow/shrink) — None keeps the vertical-only behavior
        self.replicas = replicas
        self.replica_idle_passes = int(replica_idle_passes)
        self._idle_passes: Dict[str, int] = {}
        self._last_scaled: Dict[str, float] = {}
        self.decisions: List[dict] = []
        self._watch: Optional[threading.Thread] = None
        self._watching = False

    # ------------------------------------------------------------- signal
    def _signal(self, name: str, snap: Optional[dict] = None
                ) -> Optional[dict]:
        """{queue_depth, pool_free, pool_used, n_slots} for `name`,
        read from the gauge families when monitoring is on. `snap` is
        a registry snapshot shared across one check() pass — one copy
        per pass, not one per model (snapshot copies every family
        under the registry lock the hot serving counters contend on)."""
        from deeplearning4j_tpu import monitor
        if monitor.is_enabled():
            if snap is None:
                snap = monitor.registry().snapshot()

            def val(fam):
                for e in (snap.get(fam) or {}).get("values", []):
                    if e.get("labels", {}).get("model") == name:
                        return e.get("value")
                return None

            sig = {"queue_depth": val("fleet_queue_depth"),
                   "pool_free": val("fleet_pool_blocks_free"),
                   "pool_used": val("fleet_pool_blocks_used"),
                   "n_slots": val("fleet_slot_count")}
            if all(v is not None for v in sig.values()):
                return sig
            # gauges not published yet — fall through to live state
        try:
            server = self.fleet.server(name)
        except KeyError:
            return None
        return {"queue_depth": server.queue_depth(),
                "pool_free": server.engine.pool.free_blocks,
                "pool_used": server.engine.pool.used_blocks,
                "n_slots": server.engine.n_slots}

    def _goodput(self, name: str, snap: Optional[dict]) -> Optional[float]:
        """The model's `serving_goodput_fraction` (by its `server=`
        label) from the shared snapshot, falling back to the live
        ledger when monitoring is off.  Returns None until the server
        has dispatched NON-warmup work — a warmed-but-idle server's
        0.0 fraction is absence of traffic, not waste, and must not
        read as scale-out pressure."""
        from deeplearning4j_tpu.monitor.goodput import (
            GOODPUT_COUNTER_FAMILIES)
        if snap is not None:
            frac = None
            for e in (snap.get("serving_goodput_fraction")
                      or {}).get("values", []):
                if e.get("labels", {}).get("server") == name:
                    frac = e.get("value")
            if frac is not None:
                served = 0.0
                for cls, fam in GOODPUT_COUNTER_FAMILIES.items():
                    if cls == "warmup":
                        continue
                    for e in (snap.get(fam) or {}).get("values", []):
                        if e.get("labels", {}).get("server") == name:
                            served += e.get("value") or 0.0
                return frac if served > 0 else None
        try:
            server = self.fleet.server(name)
        except KeyError:
            return None
        lg = server.engine.goodput
        if lg.dispatched_total - lg.classes["warmup"] <= 0:
            return None
        return lg.goodput_fraction()

    def _rules_pressure(self, name: str, snap: Optional[dict],
                        states: List[dict]) -> List[str]:
        """Rules-mode pressure: firing alerts targeting this model (or
        fleet-wide), plus the optional goodput floor.  `states` is one
        evaluation shared across the whole check() pass — delta-rate
        rules need real intervals between evaluations."""
        pressure = []
        by_name = {r.name: r for r in self.rules.rules}
        for s in states:
            if s["state"] != "firing":
                continue
            rule = by_name.get(s["name"])
            target = None
            if rule is not None:
                target = (rule.labels.get("model")
                          or rule.labels.get("server"))
            if target in (None, name):
                pressure.append(f"alert {s['name']} firing "
                                f"({s['severity']})")
        if self.goodput_low is not None:
            gp = self._goodput(name, snap)
            if gp is not None and gp < self.goodput_low:
                pressure.append(f"goodput fraction {gp:.2f} < "
                                f"{self.goodput_low}")
        return pressure

    # -------------------------------------------------------------- check
    def check(self, names: Optional[List[str]] = None) -> List[dict]:
        """Evaluate (and execute) scale-up decisions; returns the
        decision records made this pass (also appended to
        ``self.decisions`` for the evidence ledger)."""
        from deeplearning4j_tpu import monitor
        snap = (monitor.registry().snapshot()
                if monitor.is_enabled() else None)
        rule_states = (self.rules.evaluate()
                       if self.rules is not None else None)
        made = []
        for name in (names or self.fleet.names()):
            sig = self._signal(name, snap)
            if sig is None:
                continue
            last = self._last_scaled.get(name, 0.0)
            if time.monotonic() - last < self.cooldown_s:
                continue
            if self.rules is not None:
                pressure = self._rules_pressure(name, snap, rule_states)
            else:
                total = sig["pool_free"] + sig["pool_used"]
                free_frac = sig["pool_free"] / total if total else 1.0
                pressure = []
                if sig["queue_depth"] > self.queue_depth_high:
                    pressure.append(
                        f"queue_depth {sig['queue_depth']:.0f} > "
                        f"{self.queue_depth_high}")
                if free_frac < self.pool_free_frac_low:
                    pressure.append(
                        f"pool free fraction {free_frac:.2f} < "
                        f"{self.pool_free_frac_low}")
            if not pressure:
                rec = self._maybe_shrink_replicas(name, sig)
                if rec is not None:
                    made.append(rec)
                continue
            self._idle_passes[name] = 0
            server = self.fleet.server(name)
            cur_slots = server.engine.n_slots
            cur_blocks = server.engine.pool.n_blocks
            new_slots = min(cur_slots * self.factor, self.max_slots)
            new_blocks = min(cur_blocks * self.factor, self.max_blocks)
            if new_slots <= cur_slots and new_blocks <= cur_blocks:
                # vertical levers at their caps: go HORIZONTAL — add a
                # replica process (the router's least-loaded balancing
                # spreads traffic onto it as soon as it registers)
                rec = self._grow_replicas(name, sig, pressure)
                if rec is not None:
                    made.append(rec)
                continue
            rec = self.fleet.scale(
                name, n_slots=new_slots, n_blocks=new_blocks,
                drain_timeout=self.drain_timeout)
            rec["reason"] = "; ".join(pressure)
            rec["signal"] = sig
            self._last_scaled[name] = time.monotonic()
            self.decisions.append(rec)
            GLOBAL_FLIGHT_RECORDER.record(
                "autoscale", model=name, before=rec["before"],
                after=rec["after"], reason=rec["reason"])
            made.append(rec)
            log.info("autoscaled %s: %s -> %s (%s)", name,
                     rec["before"], rec["after"], rec["reason"])
        return made

    # ------------------------------------------------- horizontal scaling
    def _grow_replicas(self, name: str, sig: dict,
                       pressure: List[str]) -> Optional[dict]:
        if self.replicas is None or not self.replicas.grow():
            return None            # no manager, or at max_replicas
        rec = {"name": name, "action": "grow_replicas",
               "replicas": self.replicas.count(),
               "reason": "; ".join(pressure), "signal": sig}
        self._last_scaled[name] = time.monotonic()
        self.decisions.append(rec)
        GLOBAL_FLIGHT_RECORDER.record(
            "autoscale", model=name, action="grow_replicas",
            replicas=rec["replicas"], reason=rec["reason"])
        log.info("autoscaled %s horizontally: %d replicas (%s)", name,
                 rec["replicas"], rec["reason"])
        return rec

    def _maybe_shrink_replicas(self, name: str,
                               sig: dict) -> Optional[dict]:
        """No pressure this pass: one idle tick toward shrinking. Only
        a run of `replica_idle_passes` pressure-free passes WITH an
        empty admission queue releases a replica — a single quiet
        sample between bursts must not thrash the fleet."""
        if self.replicas is None:
            return None
        if (sig.get("queue_depth") or 0) > 0:
            self._idle_passes[name] = 0
            return None
        n = self._idle_passes.get(name, 0) + 1
        self._idle_passes[name] = n
        if n < self.replica_idle_passes:
            return None
        self._idle_passes[name] = 0
        if not self.replicas.shrink():
            return None            # already at min_replicas
        rec = {"name": name, "action": "shrink_replicas",
               "replicas": self.replicas.count(),
               "reason": f"idle for {n} consecutive passes",
               "signal": sig}
        self._last_scaled[name] = time.monotonic()
        self.decisions.append(rec)
        GLOBAL_FLIGHT_RECORDER.record(
            "autoscale", model=name, action="shrink_replicas",
            replicas=rec["replicas"], reason=rec["reason"])
        log.info("autoscaled %s horizontally: %d replicas (%s)", name,
                 rec["replicas"], rec["reason"])
        return rec

    # -------------------------------------------------------------- watch
    def start(self, interval_s: float = 0.5) -> "FleetAutoscaler":
        if self._watch is not None:
            return self
        self._watching = True

        def loop():
            while self._watching:
                try:
                    self.check()
                except Exception:  # noqa: BLE001 — scaling must not crash serving
                    log.exception("autoscaler pass failed (continuing)")
                time.sleep(interval_s)

        self._watch = threading.Thread(target=loop, daemon=True)
        self._watch.start()
        return self

    def stop(self):
        self._watching = False
        if self._watch is not None:
            self._watch.join(timeout=10)
            self._watch = None
