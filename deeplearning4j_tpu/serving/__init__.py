"""Serving tier: continuous-batching generation over a paged KV pool,
plus the fleet deployment plane around it.

- `serving.paged`    — block pools + host free/used accounting
- `serving.engine`   — the jitted decode/prefill/score programs +
  slot state (speculative draft-accept decoding, copy-on-write
  shared-prefix admission)
- `serving.server`   — the threaded scheduler (`GenerationServer`),
  token streams, SLO-aware shedding, the `drain()` hot-swap seam
- `serving.registry` — versioned `ModelRegistry` over ModelSerializer
  zips (one-winner publish, corrupt fallback, pinned retention,
  checkpoint-as-publish listener)
- `serving.fleet`    — `FleetServer` multi-model hosting with
  zero-downtime hot-swap + `FleetAutoscaler`
- `serving.router`   — `FleetRouter` front end (least-loaded replica
  balancing, weighted SLO shedding, transport request plane) +
  `FleetClient` + `MigratingStream`
- `serving.wire`     — request/reply/handoff frames over the streaming
  transports' ndarray wire format (typed `WireFormatError` decoding)
- `serving.replica`  — horizontal serving: `ReplicaWorker` processes
  behind the elastic coordinator, `ReplicaSet`/`ReplicaClient` on the
  router side, `ReplicaManager` + `spawn_replica` for fleets
- `serving.disagg`   — disaggregated prefill/decode workers over the
  `DLFP` paged-K/V handoff frame

See docs/SERVING.md for the scheduler model, the paged-pool
invariants, the shedding policy, the decode-parity contract, and the
fleet swap state machine.
"""

from deeplearning4j_tpu.serving.paged import (
    GARBAGE_BLOCK,
    BlockAllocator,
    PagedKVPool,
    blocks_needed,
)
from deeplearning4j_tpu.serving.engine import PagedDecodeEngine
from deeplearning4j_tpu.serving.server import (
    GenerationServer,
    ServerDrainingError,
    ServerStoppedError,
    ShedError,
    TokenStream,
)
from deeplearning4j_tpu.serving.registry import (
    ModelRegistry,
    RegistryPublishListener,
    VersionConflictError,
)
from deeplearning4j_tpu.serving.fleet import FleetAutoscaler, FleetServer
from deeplearning4j_tpu.serving.router import (
    FleetClient,
    FleetRouter,
    MigratingStream,
    RemoteTokenStream,
    UnknownModelError,
)
from deeplearning4j_tpu.serving.wire import WireFormatError
from deeplearning4j_tpu.serving.replica import (
    ReplicaClient,
    ReplicaLostError,
    ReplicaManager,
    ReplicaSet,
    ReplicaWorker,
    spawn_replica,
)
from deeplearning4j_tpu.serving.disagg import (
    DecodeWorker,
    PrefillWorker,
    run_disaggregated,
)

__all__ = [
    "GARBAGE_BLOCK", "BlockAllocator", "PagedKVPool", "blocks_needed",
    "PagedDecodeEngine", "GenerationServer", "ShedError", "TokenStream",
    "ServerDrainingError", "ServerStoppedError",
    "ModelRegistry", "RegistryPublishListener", "VersionConflictError",
    "FleetServer", "FleetAutoscaler",
    "FleetRouter", "FleetClient", "MigratingStream", "RemoteTokenStream",
    "UnknownModelError",
    "WireFormatError", "ReplicaClient", "ReplicaLostError",
    "ReplicaManager", "ReplicaSet", "ReplicaWorker", "spawn_replica",
    "PrefillWorker", "DecodeWorker", "run_disaggregated",
]
