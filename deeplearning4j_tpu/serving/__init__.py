"""Serving tier: continuous-batching generation over a paged KV pool.

- `serving.paged`  — block pools + host free/used accounting
- `serving.engine` — the jitted decode/prefill programs + slot state
- `serving.server` — the threaded scheduler (`GenerationServer`),
  token streams, SLO-aware shedding

See docs/SERVING.md for the scheduler model, the paged-pool
invariants, the shedding policy, and the decode-parity contract.
"""

from deeplearning4j_tpu.serving.paged import (
    GARBAGE_BLOCK,
    BlockAllocator,
    PagedKVPool,
    blocks_needed,
)
from deeplearning4j_tpu.serving.engine import PagedDecodeEngine
from deeplearning4j_tpu.serving.server import (
    GenerationServer,
    ShedError,
    TokenStream,
)

__all__ = [
    "GARBAGE_BLOCK", "BlockAllocator", "PagedKVPool", "blocks_needed",
    "PagedDecodeEngine", "GenerationServer", "ShedError", "TokenStream",
]
