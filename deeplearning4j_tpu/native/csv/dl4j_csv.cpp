// dl4j_csv — native CSV -> float32 matrix parser.
//
// Reference parity: DataVec's record-reading path (CSVRecordReader +
// the RecordReaderDataSetIterator pipeline) is JVM-native; the TPU
// framework's equivalent hot path is this single-pass C++ parser:
// mmap-free buffered read, strtof-driven field scan, quote-aware,
// comment/blank-line skipping. Consumed via ctypes
// (deeplearning4j_tpu/datasets/native_csv.py) with a NumPy fallback
// when no toolchain is present.
//
// Build: g++ -O3 -fPIC -shared dl4j_csv.cpp -o libdl4j_csv.so

#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <string>
#include <vector>

extern "C" {

// Scans the file once: number of data rows and the column count of the
// first data row. Returns 0 on success, negative on error.
//   skip_rows: header lines to skip; delim: field delimiter.
int dl4j_csv_shape(const char *path, char delim, long skip_rows,
                   long *rows_out, long *cols_out) {
    FILE *f = fopen(path, "rb");
    if (!f)
        return -1;
    std::string line;
    long rows = 0, cols = 0, lineno = 0;
    int c;
    line.reserve(4096);
    for (;;) {
        c = fgetc(f);
        if (c != EOF && c != '\n') {
            line.push_back((char)c);
            continue;
        }
        if (!line.empty() && line.back() == '\r')
            line.pop_back();
        bool end = (c == EOF);
        if (!line.empty() && line[0] != '#') {
            if (lineno >= skip_rows) {
                if (rows == 0) {
                    long n = 1;
                    bool quoted = false;
                    for (char ch : line) {
                        if (ch == '"')
                            quoted = !quoted;
                        else if (ch == delim && !quoted)
                            n++;
                    }
                    cols = n;
                }
                rows++;
            }
            lineno++;
        }
        line.clear();
        if (end)
            break;
    }
    fclose(f);
    *rows_out = rows;
    *cols_out = cols;
    return 0;
}

// Parses into the caller's [rows x cols] float32 buffer (row-major).
// Fields that fail to parse as numbers become NaN (the Python layer
// decides policy). Returns rows actually parsed, negative on error.
long dl4j_csv_parse(const char *path, char delim, long skip_rows,
                    float *out, long rows, long cols) {
    FILE *f = fopen(path, "rb");
    if (!f)
        return -1;
    std::string line;
    long r = 0, lineno = 0;
    int c;
    line.reserve(4096);
    for (;;) {
        c = fgetc(f);
        if (c != EOF && c != '\n') {
            line.push_back((char)c);
            continue;
        }
        if (!line.empty() && line.back() == '\r')
            line.pop_back();
        bool end = (c == EOF);
        if (!line.empty() && line[0] != '#') {
            if (lineno >= skip_rows && r < rows) {
                const char *p = line.c_str();
                for (long j = 0; j < cols; j++) {
                    // field span first (quote-aware, starting from the
                    // field head so quote state is always correct),
                    // THEN parse the value inside the span
                    const char *q = p;
                    bool quoted = false;
                    while (*q && (quoted || *q != delim)) {
                        if (*q == '"')
                            quoted = !quoted;
                        q++;
                    }
                    const char *fs = p;
                    while (*fs == ' ' || *fs == '"')
                        fs++;
                    char *endp = nullptr;
                    float v = strtof(fs, &endp);
                    out[r * cols + j] =
                        (endp == fs) ? __builtin_nanf("") : v;
                    p = (*q == delim) ? q + 1 : q;
                }
                r++;
            }
            lineno++;
        }
        line.clear();
        if (end)
            break;
    }
    fclose(f);
    return r;
}

}  // extern "C"
