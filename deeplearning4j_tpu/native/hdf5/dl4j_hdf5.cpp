// dl4j_hdf5 — minimal C++ HDF5 reader/writer for Keras model import.
//
// Reference parity: deeplearning4j-modelimport's Hdf5Archive.java binds
// native libhdf5 through JavaCPP (`Hdf5Archive.java:25,37,51,57-58`);
// this library plays the same role for the TPU framework: a thin native
// layer over libhdf5 exposing exactly the operations Keras import
// needs (string attributes, dataset read/write, group creation),
// consumed from Python via ctypes (modelimport/hdf5.py).
//
// The image ships libhdf5_serial.so without headers, so the needed C
// API surface (HDF5 1.10 ABI: hid_t = int64) is declared here directly.
//
// Build: g++ -O2 -fPIC -shared dl4j_hdf5.cpp -o libdl4j_hdf5.so \
//        -l:libhdf5_serial.so.103
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <string>
#include <vector>

extern "C" {

// ----------------------------------------------------------------- HDF5 ABI
typedef int64_t hid_t;
typedef int herr_t;
typedef unsigned long long hsize_t;
typedef int htri_t;

#define H5P_DEFAULT ((hid_t)0)
#define H5S_ALL ((hid_t)0)
#define H5F_ACC_RDONLY 0u
#define H5F_ACC_TRUNC 2u
#define H5T_VARIABLE ((size_t)-1)
#define H5S_SCALAR 0

herr_t H5open(void);
hid_t H5Fopen(const char *, unsigned, hid_t);
hid_t H5Fcreate(const char *, unsigned, hid_t, hid_t);
herr_t H5Fclose(hid_t);
hid_t H5Gcreate2(hid_t, const char *, hid_t, hid_t, hid_t);
herr_t H5Gclose(hid_t);
hid_t H5Oopen(hid_t, const char *, hid_t);
herr_t H5Oclose(hid_t);
hid_t H5Dopen2(hid_t, const char *, hid_t);
herr_t H5Dclose(hid_t);
hid_t H5Dget_space(hid_t);
hid_t H5Dget_type(hid_t);
herr_t H5Dread(hid_t, hid_t, hid_t, hid_t, hid_t, void *);
hid_t H5Dcreate2(hid_t, const char *, hid_t, hid_t, hid_t, hid_t, hid_t);
herr_t H5Dwrite(hid_t, hid_t, hid_t, hid_t, hid_t, const void *);
hid_t H5Screate(int);
hid_t H5Screate_simple(int, const hsize_t *, const hsize_t *);
int H5Sget_simple_extent_ndims(hid_t);
int H5Sget_simple_extent_dims(hid_t, hsize_t *, hsize_t *);
hsize_t H5Sget_simple_extent_npoints(hid_t);
herr_t H5Sclose(hid_t);
hid_t H5Aopen(hid_t, const char *, hid_t);
hid_t H5Acreate2(hid_t, const char *, hid_t, hid_t, hid_t, hid_t);
herr_t H5Aread(hid_t, hid_t, void *);
herr_t H5Awrite(hid_t, hid_t, const void *);
hid_t H5Aget_type(hid_t);
hid_t H5Aget_space(hid_t);
herr_t H5Aclose(hid_t);
htri_t H5Aexists(hid_t, const char *);
hid_t H5Tcopy(hid_t);
herr_t H5Tset_size(hid_t, size_t);
size_t H5Tget_size(hid_t);
htri_t H5Tis_variable_str(hid_t);
herr_t H5Tclose(hid_t);
htri_t H5Lexists(hid_t, const char *, hid_t);
herr_t H5Eset_auto2(hid_t, void *, void *);
hid_t H5Gopen2(hid_t, const char *, hid_t);
typedef struct H5L_info_t H5L_info_t;
typedef herr_t (*H5L_iterate_t)(hid_t, const char *, const H5L_info_t *,
                                void *);
// H5Literate is a macro in 1.14 (symbol H5Literate1); weak-declare both
// spellings and pick whichever the loaded libhdf5 exports.
extern herr_t H5Literate(hid_t, int, int, hsize_t *, H5L_iterate_t, void *)
    __attribute__((weak));
extern herr_t H5Literate1(hid_t, int, int, hsize_t *, H5L_iterate_t, void *)
    __attribute__((weak));

// global type ids (the H5T_NATIVE_* macros resolve to these globals)
extern hid_t H5T_C_S1_g;
extern hid_t H5T_NATIVE_FLOAT_g;
extern hid_t H5T_NATIVE_DOUBLE_g;
extern hid_t H5T_NATIVE_INT_g;
extern hid_t H5T_NATIVE_LLONG_g;

// ----------------------------------------------------------------- helpers
static bool g_inited = false;
static void ensure_init() {
  if (!g_inited) {
    H5open();
    H5Eset_auto2(0, nullptr, nullptr);  // silence stderr spew; we return codes
    g_inited = true;
  }
}

// --------------------------------------------------------------- file ops
int64_t dl4j_h5_open(const char *path) {
  ensure_init();
  return (int64_t)H5Fopen(path, H5F_ACC_RDONLY, H5P_DEFAULT);
}

int64_t dl4j_h5_create(const char *path) {
  ensure_init();
  return (int64_t)H5Fcreate(path, H5F_ACC_TRUNC, H5P_DEFAULT, H5P_DEFAULT);
}

int dl4j_h5_close(int64_t file) { return (int)H5Fclose((hid_t)file); }

int dl4j_h5_exists(int64_t file, const char *path) {
  // checks each component so intermediate groups may be missing
  std::string p(path);
  std::string cur;
  size_t start = p[0] == '/' ? 1 : 0;
  while (start <= p.size()) {
    size_t slash = p.find('/', start);
    if (slash == std::string::npos) slash = p.size();
    cur += "/" + p.substr(start, slash - start);
    if (H5Lexists((hid_t)file, cur.c_str(), H5P_DEFAULT) <= 0) return 0;
    start = slash + 1;
  }
  return 1;
}

int dl4j_h5_create_group(int64_t file, const char *path) {
  hid_t g = H5Gcreate2((hid_t)file, path, H5P_DEFAULT, H5P_DEFAULT, H5P_DEFAULT);
  if (g < 0) return -1;
  H5Gclose(g);
  return 0;
}

// ------------------------------------------------------------ attributes
// Read a string attribute (scalar or 1-D array; fixed or variable-length)
// on the object at `obj_path`. Multiple values are '\n'-joined into
// `out` (caller-allocated, out_len bytes). Returns #values or -1.
int dl4j_h5_read_string_attr(int64_t file, const char *obj_path,
                             const char *attr_name, char *out,
                             int64_t out_len) {
  ensure_init();
  hid_t obj = H5Oopen((hid_t)file, obj_path, H5P_DEFAULT);
  if (obj < 0) return -1;
  if (H5Aexists(obj, attr_name) <= 0) { H5Oclose(obj); return -1; }
  hid_t attr = H5Aopen(obj, attr_name, H5P_DEFAULT);
  if (attr < 0) { H5Oclose(obj); return -1; }
  hid_t ftype = H5Aget_type(attr);
  hid_t space = H5Aget_space(attr);
  hsize_t n = H5Sget_simple_extent_npoints(space);
  if (n == 0) n = 1;
  std::string joined;
  int count = 0;
  if (H5Tis_variable_str(ftype) > 0) {
    hid_t mtype = H5Tcopy(H5T_C_S1_g);
    H5Tset_size(mtype, H5T_VARIABLE);
    std::vector<char *> bufs(n, nullptr);
    if (H5Aread(attr, mtype, bufs.data()) >= 0) {
      for (hsize_t i = 0; i < n; i++) {
        if (i) joined += "\n";
        if (bufs[i]) { joined += bufs[i]; free(bufs[i]); }
        count++;
      }
    }
    H5Tclose(mtype);
  } else {
    size_t sz = H5Tget_size(ftype);
    std::vector<char> buf(n * (sz + 1), 0);
    hid_t mtype = H5Tcopy(H5T_C_S1_g);
    H5Tset_size(mtype, sz + 1);  // room for forced NUL
    // read with the FILE type then re-chunk (fixed strings may lack NUL)
    std::vector<char> raw(n * sz, 0);
    if (H5Aread(attr, ftype, raw.data()) >= 0) {
      for (hsize_t i = 0; i < n; i++) {
        if (i) joined += "\n";
        std::string s(raw.data() + i * sz, sz);
        s.resize(strnlen(s.c_str(), sz));
        joined += s;
        count++;
      }
    }
    H5Tclose(mtype);
  }
  H5Tclose(ftype);
  H5Sclose(space);
  H5Aclose(attr);
  H5Oclose(obj);
  if ((int64_t)joined.size() + 1 > out_len) return -2;  // buffer too small
  memcpy(out, joined.c_str(), joined.size() + 1);
  return count;
}

// Write a scalar fixed-length string attribute.
int dl4j_h5_write_string_attr(int64_t file, const char *obj_path,
                              const char *attr_name, const char *value) {
  hid_t obj = H5Oopen((hid_t)file, obj_path, H5P_DEFAULT);
  if (obj < 0) return -1;
  size_t len = strlen(value);
  hid_t type = H5Tcopy(H5T_C_S1_g);
  H5Tset_size(type, len > 0 ? len : 1);
  hid_t space = H5Screate(H5S_SCALAR);
  hid_t attr = H5Acreate2(obj, attr_name, type, space, H5P_DEFAULT, H5P_DEFAULT);
  int rc = -1;
  if (attr >= 0) {
    rc = (int)H5Awrite(attr, type, value);
    H5Aclose(attr);
  }
  H5Sclose(space);
  H5Tclose(type);
  H5Oclose(obj);
  return rc;
}

// Write a 1-D fixed-length string-array attribute; `values` are
// '\n'-separated.
int dl4j_h5_write_string_array_attr(int64_t file, const char *obj_path,
                                    const char *attr_name,
                                    const char *values) {
  std::vector<std::string> items;
  std::string cur;
  for (const char *p = values;; p++) {
    if (*p == '\n' || *p == '\0') {
      items.push_back(cur);
      cur.clear();
      if (*p == '\0') break;
    } else {
      cur += *p;
    }
  }
  size_t maxlen = 1;
  for (auto &s : items) maxlen = s.size() > maxlen ? s.size() : maxlen;
  hid_t obj = H5Oopen((hid_t)file, obj_path, H5P_DEFAULT);
  if (obj < 0) return -1;
  hid_t type = H5Tcopy(H5T_C_S1_g);
  H5Tset_size(type, maxlen);
  hsize_t n = items.size();
  hid_t space = H5Screate_simple(1, &n, nullptr);
  std::vector<char> buf(n * maxlen, 0);
  for (size_t i = 0; i < items.size(); i++)
    memcpy(buf.data() + i * maxlen, items[i].c_str(), items[i].size());
  hid_t attr = H5Acreate2(obj, attr_name, type, space, H5P_DEFAULT, H5P_DEFAULT);
  int rc = -1;
  if (attr >= 0) {
    rc = (int)H5Awrite(attr, type, buf.data());
    H5Aclose(attr);
  }
  H5Sclose(space);
  H5Tclose(type);
  H5Oclose(obj);
  return rc;
}

// ---------------------------------------------------------------- listing
static herr_t dl4j_list_cb(hid_t, const char *name, const H5L_info_t *,
                           void *op) {
  auto *s = (std::string *)op;
  if (!s->empty()) *s += "\n";
  *s += name;
  return 0;
}

// List immediate child link names of the group at `path`, '\n'-joined,
// in ascending name order. Returns #children, -1 on error, -2 if the
// caller buffer is too small.
int dl4j_h5_list_children(int64_t file, const char *path, char *out,
                          int64_t out_len) {
  ensure_init();
  hid_t g = H5Gopen2((hid_t)file, path, H5P_DEFAULT);
  if (g < 0) return -1;
  std::string names;
  herr_t rc = -1;
  // H5_INDEX_NAME = 0, H5_ITER_INC = 0
  if (&H5Literate != nullptr)
    rc = H5Literate(g, 0, 0, nullptr, dl4j_list_cb, &names);
  else if (&H5Literate1 != nullptr)
    rc = H5Literate1(g, 0, 0, nullptr, dl4j_list_cb, &names);
  H5Gclose(g);
  if (rc < 0) return -1;
  if ((int64_t)names.size() + 1 > out_len) return -2;
  memcpy(out, names.c_str(), names.size() + 1);
  int count = names.empty() ? 0 : 1;
  for (char c : names)
    if (c == '\n') count++;
  return count;
}

// -------------------------------------------------------------- datasets
// Shape query: fills dims[0..ndim-1], returns ndim or -1.
int dl4j_h5_dataset_ndim(int64_t file, const char *path, int64_t *dims,
                         int max_ndim) {
  hid_t ds = H5Dopen2((hid_t)file, path, H5P_DEFAULT);
  if (ds < 0) return -1;
  hid_t space = H5Dget_space(ds);
  int nd = H5Sget_simple_extent_ndims(space);
  if (nd >= 0 && nd <= max_ndim) {
    std::vector<hsize_t> hd(nd > 0 ? nd : 1);
    H5Sget_simple_extent_dims(space, hd.data(), nullptr);
    for (int i = 0; i < nd; i++) dims[i] = (int64_t)hd[i];
  }
  H5Sclose(space);
  H5Dclose(ds);
  return nd;
}

// Read full dataset as float32 into caller buffer.
int dl4j_h5_read_dataset_f32(int64_t file, const char *path, float *out) {
  hid_t ds = H5Dopen2((hid_t)file, path, H5P_DEFAULT);
  if (ds < 0) return -1;
  herr_t rc = H5Dread(ds, H5T_NATIVE_FLOAT_g, H5S_ALL, H5S_ALL, H5P_DEFAULT, out);
  H5Dclose(ds);
  return (int)rc;
}

// Create + write a float32 dataset.
int dl4j_h5_write_dataset_f32(int64_t file, const char *path,
                              const int64_t *dims, int ndim,
                              const float *data) {
  std::vector<hsize_t> hd(ndim > 0 ? ndim : 1);
  for (int i = 0; i < ndim; i++) hd[i] = (hsize_t)dims[i];
  hid_t space = ndim > 0 ? H5Screate_simple(ndim, hd.data(), nullptr)
                         : H5Screate(H5S_SCALAR);
  hid_t ds = H5Dcreate2((hid_t)file, path, H5T_NATIVE_FLOAT_g, space,
                        H5P_DEFAULT, H5P_DEFAULT, H5P_DEFAULT);
  int rc = -1;
  if (ds >= 0) {
    rc = (int)H5Dwrite(ds, H5T_NATIVE_FLOAT_g, H5S_ALL, H5S_ALL, H5P_DEFAULT,
                       data);
    H5Dclose(ds);
  }
  H5Sclose(space);
  return rc;
}

}  // extern "C"
