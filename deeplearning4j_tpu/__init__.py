"""deeplearning4j_tpu — a TPU-native deep learning framework.

Capability-equivalent rebuild of the deeplearning4j stack (reference:
arthuremanuel/deeplearning4j @ 0.9.2-SNAPSHOT) designed TPU-first on
JAX/XLA: params are pytrees, gradients come from ``jax.value_and_grad``,
device parallelism is a sharding annotation over a ``jax.sharding.Mesh``
(not thread-per-device wrappers), and every hot op compiles onto the MXU
through XLA.

Package map (mirrors the reference's layer map, SURVEY.md §1):

- ``nd``        tensor substrate shim (dtype policy, RNG streams) —
                stands in for ND4J/libnd4j.
- ``common``    activations / losses / updaters / schedules / weight init —
                ND4J's IActivation / ILossFunction / IUpdater surface.
- ``nn``        layer configs (config-as-data DSL), functional layer
                implementations, MultiLayerNetwork & ComputationGraph
                containers (reference: deeplearning4j-nn).
- ``optimize``  listeners + training utilities (reference: optimize/).
- ``eval``      Evaluation / RegressionEvaluation / ROC (reference: eval/).
- ``datasets``  DataSet, iterators, fetchers (reference: datasets/).
- ``parallel``  SPMD mesh training — the single engine replacing
                ParallelWrapper, ParameterAveraging and SharedTraining
                (reference: deeplearning4j-scaleout).
- ``zoo``       model zoo (reference: deeplearning4j-zoo).
- ``nlp``       sequence-vector embedding stack (reference: deeplearning4j-nlp).
- ``keras``     Keras model import (reference: deeplearning4j-modelimport).
- ``util``      model serialization & helpers.
"""

__version__ = "0.1.0"

from deeplearning4j_tpu.nd import dtype as _dtype  # noqa: F401
