"""Keras model import (reference: deeplearning4j-modelimport, SURVEY §2.8).

Native HDF5 access goes through the C++ shim `native/hdf5/dl4j_hdf5.cpp`
(the reference binds libhdf5 via JavaCPP in `Hdf5Archive.java`; here the
binding is ctypes → our C++ lib → libhdf5_serial).
"""

from deeplearning4j_tpu.modelimport.hdf5 import Hdf5Archive
from deeplearning4j_tpu.modelimport.keras import KerasModelImport
