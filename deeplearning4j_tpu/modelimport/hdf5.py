"""Hdf5Archive — ctypes binding over the C++ HDF5 shim.

Reference: `modelimport/keras/Hdf5Archive.java` (378 LoC) which walks
HDF5 via JavaCPP's libhdf5 binding. Same shape here: the native library
(native/hdf5/dl4j_hdf5.cpp, compiled on first use) exposes string-attr
reads, dataset read/write and group creation; this class is the typed
Python surface. Writing is included so tests can fabricate golden Keras
.h5 files without h5py.
"""

from __future__ import annotations

import ctypes
from pathlib import Path
from typing import List, Optional, Sequence

import numpy as np

from deeplearning4j_tpu.util.native_build import NATIVE_ROOT, build

_SRC = NATIVE_ROOT / "hdf5" / "dl4j_hdf5.cpp"

_lib = None


def _load_lib():
    global _lib
    if _lib is not None:
        return _lib
    so = build(_SRC, "libdl4j_hdf5.so",
               link_candidates=["-l:libhdf5_serial.so.103",
                                "-l:libhdf5_serial.so.100",
                                "-lhdf5_serial", "-lhdf5"])
    lib = ctypes.CDLL(str(so))
    lib.dl4j_h5_open.restype = ctypes.c_int64
    lib.dl4j_h5_open.argtypes = [ctypes.c_char_p]
    lib.dl4j_h5_create.restype = ctypes.c_int64
    lib.dl4j_h5_create.argtypes = [ctypes.c_char_p]
    lib.dl4j_h5_close.argtypes = [ctypes.c_int64]
    lib.dl4j_h5_exists.argtypes = [ctypes.c_int64, ctypes.c_char_p]
    lib.dl4j_h5_create_group.argtypes = [ctypes.c_int64, ctypes.c_char_p]
    lib.dl4j_h5_read_string_attr.argtypes = [
        ctypes.c_int64, ctypes.c_char_p, ctypes.c_char_p, ctypes.c_char_p,
        ctypes.c_int64]
    lib.dl4j_h5_write_string_attr.argtypes = [
        ctypes.c_int64, ctypes.c_char_p, ctypes.c_char_p, ctypes.c_char_p]
    lib.dl4j_h5_write_string_array_attr.argtypes = [
        ctypes.c_int64, ctypes.c_char_p, ctypes.c_char_p, ctypes.c_char_p]
    lib.dl4j_h5_list_children.argtypes = [
        ctypes.c_int64, ctypes.c_char_p, ctypes.c_char_p, ctypes.c_int64]
    lib.dl4j_h5_dataset_ndim.argtypes = [
        ctypes.c_int64, ctypes.c_char_p, ctypes.POINTER(ctypes.c_int64),
        ctypes.c_int]
    lib.dl4j_h5_read_dataset_f32.argtypes = [
        ctypes.c_int64, ctypes.c_char_p, ctypes.POINTER(ctypes.c_float)]
    lib.dl4j_h5_write_dataset_f32.argtypes = [
        ctypes.c_int64, ctypes.c_char_p, ctypes.POINTER(ctypes.c_int64),
        ctypes.c_int, ctypes.POINTER(ctypes.c_float)]
    _lib = lib
    return lib


class Hdf5Archive:
    def __init__(self, path, mode: str = "r"):
        self._lib = _load_lib()
        path = str(path)
        if mode == "r":
            self._f = self._lib.dl4j_h5_open(path.encode())
        elif mode == "w":
            self._f = self._lib.dl4j_h5_create(path.encode())
        else:
            raise ValueError(mode)
        if self._f <= 0:
            raise IOError(f"Cannot open HDF5 file {path} (mode={mode})")

    def close(self):
        if self._f > 0:
            self._lib.dl4j_h5_close(self._f)
            self._f = -1

    def __enter__(self):
        return self

    def __exit__(self, *a):
        self.close()

    # ------------------------------------------------------------- reads
    def exists(self, path: str) -> bool:
        return bool(self._lib.dl4j_h5_exists(self._f, path.encode()))

    def read_attr_string(self, attr: str, obj_path: str = "/") -> Optional[str]:
        size = 1 << 20
        while size <= (1 << 28):  # last size tried: 256 MiB
            buf = ctypes.create_string_buffer(size)
            n = self._lib.dl4j_h5_read_string_attr(
                self._f, obj_path.encode(), attr.encode(), buf, len(buf))
            if n == -2:  # buffer too small — grow and retry
                size *= 4
                continue
            return None if n < 0 else buf.value.decode("utf-8")
        raise IOError(f"Attribute {obj_path}@{attr} exceeds 256 MiB")

    def read_attr_strings(self, attr: str, obj_path: str = "/") -> List[str]:
        s = self.read_attr_string(attr, obj_path)
        return [] if s is None else ([] if s == "" else s.split("\n"))

    def list_children(self, path: str = "/") -> List[str]:
        """Immediate child link names of a group (name-ascending)."""
        size = 1 << 16
        while size <= (1 << 24):  # last size tried: 16 MiB
            buf = ctypes.create_string_buffer(size)
            n = self._lib.dl4j_h5_list_children(
                self._f, path.encode(), buf, len(buf))
            if n == -2:
                size *= 4
                continue
            if n < 0:
                raise KeyError(f"No group {path}")
            s = buf.value.decode("utf-8")
            return [] if s == "" else s.split("\n")
        raise IOError(f"Group listing for {path} exceeds 16 MiB")

    def read_dataset(self, path: str) -> np.ndarray:
        dims = (ctypes.c_int64 * 16)()
        nd = self._lib.dl4j_h5_dataset_ndim(self._f, path.encode(), dims, 16)
        if nd < 0:
            raise KeyError(f"No dataset {path}")
        shape = tuple(int(dims[i]) for i in range(nd))
        out = np.zeros(shape if shape else (1,), np.float32)
        rc = self._lib.dl4j_h5_read_dataset_f32(
            self._f, path.encode(), out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)))
        if rc < 0:
            raise IOError(f"Read failed for {path}")
        return out.reshape(shape) if shape else out[0]

    # ------------------------------------------------------------ writes
    def create_group(self, path: str):
        self._lib.dl4j_h5_create_group(self._f, path.encode())

    def write_attr_string(self, attr: str, value: str, obj_path: str = "/"):
        rc = self._lib.dl4j_h5_write_string_attr(
            self._f, obj_path.encode(), attr.encode(), value.encode())
        if rc < 0:
            raise IOError(f"Attr write failed: {obj_path}@{attr}")

    def write_attr_strings(self, attr: str, values: Sequence[str],
                           obj_path: str = "/"):
        rc = self._lib.dl4j_h5_write_string_array_attr(
            self._f, obj_path.encode(), attr.encode(),
            "\n".join(values).encode())
        if rc < 0:
            raise IOError(f"Attr write failed: {obj_path}@{attr}")

    def write_dataset(self, path: str, data: np.ndarray):
        data = np.ascontiguousarray(data, np.float32)
        dims = (ctypes.c_int64 * max(data.ndim, 1))(*data.shape)
        rc = self._lib.dl4j_h5_write_dataset_f32(
            self._f, path.encode(), dims, data.ndim,
            data.ctypes.data_as(ctypes.POINTER(ctypes.c_float)))
        if rc < 0:
            raise IOError(f"Dataset write failed: {path}")
