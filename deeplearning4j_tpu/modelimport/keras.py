"""KerasModelImport — Keras 1 & 2 .h5 → framework models.

Reference: `modelimport/keras/KerasModelImport.java:50-194` (entry
points), `KerasModel.java:57` (config parse :175, graph build :276,
weight copy :364-380 → `KerasModelUtils.copyWeightsToModel:59`), dialect
tables `config/Keras1LayerConfiguration.java` /
`Keras2LayerConfiguration.java`, and the per-layer `layers/**` mapping
classes.

Layout notes (TPU-native NHWC):
- Dense kernel [in, out] → "W" directly.
- Conv2D kernel [kh, kw, in, out] (TF/Keras2) → HWIO "W" directly;
  Keras 1 Theano kernels [out, in, kh, kw] are transposed + flipped.
- LSTM kernels are gate-reordered Keras IFCO → framework IFOG
  (`KerasLstm.java` does the same gate shuffling for DL4J's order).
- BatchNorm gamma/beta are params; moving mean/var land in net state.
"""

from __future__ import annotations

import json
import re
from typing import Dict, List, Optional, Tuple

import numpy as np

from deeplearning4j_tpu.common.updaters import Adam
from deeplearning4j_tpu.modelimport.hdf5 import Hdf5Archive
from deeplearning4j_tpu.nn.conf import InputType, NeuralNetConfiguration
from deeplearning4j_tpu.nn.conf.graph import ElementWiseVertex, MergeVertex
from deeplearning4j_tpu.nn.graph import ComputationGraph, ComputationGraphConfiguration
from deeplearning4j_tpu.nn.layers import (
    LSTM,
    ActivationLayer,
    BatchNormalization,
    Convolution1DLayer,
    ConvolutionLayer,
    DenseLayer,
    DropoutLayer,
    EmbeddingLayer,
    GlobalPoolingLayer,
    LastTimeStep,
    LocalResponseNormalization,
    LossLayer,
    OutputLayer,
    PermuteLayer,
    PoolHelperLayer,
    ReshapeLayer,
    SeparableConvolution2D,
    SimpleRnn,
    Subsampling1DLayer,
    SubsamplingLayer,
    Upsampling1D,
    Upsampling2D,
    ZeroPadding1DLayer,
    ZeroPaddingLayer,
)
from deeplearning4j_tpu.nn.layers.convolution import ConvolutionMode, PoolingMode
from deeplearning4j_tpu.nn.layers.pooling import PoolingType
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

_ACTIVATIONS = {
    "relu": "relu", "sigmoid": "sigmoid", "tanh": "tanh", "softmax": "softmax",
    "linear": "identity", "softplus": "softplus", "softsign": "softsign",
    "elu": "elu", "selu": "selu", "hard_sigmoid": "hardsigmoid",
    "swish": "swish", "gelu": "gelu", "relu6": "relu6",
}


def _act(name):
    if name is None:
        return "identity"
    return _ACTIVATIONS.get(name, name)


def _conv_mode(cfg):
    # Keras2 "padding" / Keras1 "border_mode"
    pad = cfg.get("padding", cfg.get("border_mode", "valid"))
    return ConvolutionMode.SAME if pad == "same" else ConvolutionMode.TRUNCATE


def _pair(v, default=(1, 1)):
    if v is None:
        return default
    if isinstance(v, (list, tuple)):
        return tuple(int(x) for x in v)
    return (int(v), int(v))


class KerasLayerMapper:
    """One Keras layer dict → zero or more framework layers.

    Handles both dialects: Keras 1 (`output_dim`, `nb_filter`,
    `nb_row/nb_col`, `subsample`, `border_mode`, `init`) and Keras 2
    (`units`, `filters`, `kernel_size`, `strides`, `padding`)."""

    def map(self, class_name: str, cfg: dict) -> List:
        m = getattr(self, f"_map_{class_name.lower()}", None)
        if m is None:
            raise ValueError(f"Unsupported Keras layer: {class_name}")
        out = m(cfg)
        return out if isinstance(out, list) else [out]

    # ---- core ----
    def _units(self, cfg):
        return int(cfg.get("units", cfg.get("output_dim", 0)))

    def _map_dense(self, cfg):
        return DenseLayer(n_out=self._units(cfg),
                          activation=_act(cfg.get("activation")),
                          has_bias=cfg.get("use_bias", cfg.get("bias", True)),
                          name=cfg.get("name"))

    def _map_activation(self, cfg):
        return ActivationLayer(activation=_act(cfg.get("activation")),
                               name=cfg.get("name"))

    def _map_leakyrelu(self, cfg):
        # Keras 1/2 "alpha", Keras 3 "negative_slope"; default 0.3
        alpha = cfg.get("negative_slope", cfg.get("alpha", 0.3))
        return ActivationLayer(activation=f"leakyrelu:{alpha}",
                               name=cfg.get("name"))

    def _map_dropout(self, cfg):
        # Keras rate = DROP fraction; framework dropout = RETAIN prob
        rate = cfg.get("rate", cfg.get("p", 0.5))
        return DropoutLayer(dropout=1.0 - float(rate), name=cfg.get("name"))

    def _map_flatten(self, cfg):
        return []  # automatic CNN→FF preprocessor insertion handles this

    def _map_masking(self, cfg):
        return []  # masks are explicit in this framework's fit/eval API

    # ---- conv ----
    def _map_conv2d(self, cfg):
        kernel = _pair(cfg.get("kernel_size",
                               (cfg.get("nb_row"), cfg.get("nb_col"))
                               if cfg.get("nb_row") else None), (3, 3))
        return ConvolutionLayer(
            n_out=int(cfg.get("filters", cfg.get("nb_filter", 0))),
            kernel_size=kernel,
            stride=_pair(cfg.get("strides", cfg.get("subsample"))),
            dilation=_pair(cfg.get("dilation_rate",
                                   cfg.get("atrous_rate", (1, 1)))),
            convolution_mode=_conv_mode(cfg),
            activation=_act(cfg.get("activation")),
            has_bias=cfg.get("use_bias", cfg.get("bias", True)),
            name=cfg.get("name"))

    _map_convolution2d = _map_conv2d  # Keras 1 name

    def _map_conv1d(self, cfg):
        k = cfg.get("kernel_size", cfg.get("filter_length", 3))
        k = k[0] if isinstance(k, (list, tuple)) else k
        s = cfg.get("strides", cfg.get("subsample_length", 1))
        s = s[0] if isinstance(s, (list, tuple)) else s
        d = cfg.get("dilation_rate", cfg.get("atrous_rate", 1))
        d = d[0] if isinstance(d, (list, tuple)) else d
        return Convolution1DLayer(
            n_out=int(cfg.get("filters", cfg.get("nb_filter", 0))),
            kernel_size=int(k), stride=int(s), dilation=(int(d), 1),
            convolution_mode=_conv_mode(cfg),
            activation=_act(cfg.get("activation")),
            name=cfg.get("name"))

    _map_convolution1d = _map_conv1d

    def _map_maxpooling2d(self, cfg):
        return SubsamplingLayer(
            pooling_type=PoolingMode.MAX,
            kernel_size=_pair(cfg.get("pool_size"), (2, 2)),
            stride=_pair(cfg.get("strides", cfg.get("pool_size")), (2, 2)),
            convolution_mode=_conv_mode(cfg), name=cfg.get("name"))

    def _map_averagepooling2d(self, cfg):
        layer = self._map_maxpooling2d(cfg)
        layer.pooling_type = PoolingMode.AVG
        return layer

    def _map_maxpooling1d(self, cfg):
        k = cfg.get("pool_size", cfg.get("pool_length", 2))
        k = k[0] if isinstance(k, (list, tuple)) else k
        s = cfg.get("strides", cfg.get("stride")) or k
        s = s[0] if isinstance(s, (list, tuple)) else s
        return Subsampling1DLayer(kernel_size=int(k), stride=int(s),
                                  convolution_mode=_conv_mode(cfg),
                                  name=cfg.get("name"))

    def _map_averagepooling1d(self, cfg):
        layer = self._map_maxpooling1d(cfg)
        layer.pooling_type = PoolingMode.AVG
        return layer

    def _map_globalmaxpooling2d(self, cfg):
        return GlobalPoolingLayer(pooling_type=PoolingType.MAX, name=cfg.get("name"))

    def _map_globalaveragepooling2d(self, cfg):
        return GlobalPoolingLayer(pooling_type=PoolingType.AVG, name=cfg.get("name"))

    _map_globalmaxpooling1d = _map_globalmaxpooling2d
    _map_globalaveragepooling1d = _map_globalaveragepooling2d

    def _map_zeropadding2d(self, cfg):
        pad = cfg.get("padding", 1)
        return ZeroPaddingLayer(pad=pad if isinstance(pad, int) else tuple(
            tuple(p) if isinstance(p, (list, tuple)) else (p, p) for p in pad),
            name=cfg.get("name"))

    def _map_upsampling2d(self, cfg):
        return Upsampling2D(size=_pair(cfg.get("size"), (2, 2)),
                            name=cfg.get("name"))

    # ---- recurrent / embedding ----
    def _map_embedding(self, cfg):
        return EmbeddingLayer(n_in=int(cfg.get("input_dim", 0)),
                              n_out=int(cfg.get("output_dim", 0)),
                              has_bias=False, name=cfg.get("name"))

    def _map_lstm(self, cfg):
        layers = [LSTM(n_out=self._units(cfg),
                       activation=_act(cfg.get("activation", "tanh")),
                       gate_activation=_act(cfg.get("recurrent_activation",
                                                    cfg.get("inner_activation",
                                                            "hard_sigmoid"))),
                       name=cfg.get("name"))]
        if not cfg.get("return_sequences", False):
            layers.append(LastTimeStep())
        return layers

    def _map_simplernn(self, cfg):
        layers = [SimpleRnn(n_out=self._units(cfg),
                            activation=_act(cfg.get("activation", "tanh")),
                            name=cfg.get("name"))]
        if not cfg.get("return_sequences", False):
            layers.append(LastTimeStep())
        return layers

    # ---- normalization ----
    def _map_batchnormalization(self, cfg):
        return BatchNormalization(eps=float(cfg.get("epsilon", 1e-3)),
                                  decay=float(cfg.get("momentum", 0.99)),
                                  name=cfg.get("name"))

    def _map_lrn(self, cfg):
        # custom layer in Theano-era zoo files (reference KerasLRN)
        return LocalResponseNormalization(
            k=float(cfg.get("k", 2.0)), n=int(cfg.get("n", 5)),
            alpha=float(cfg.get("alpha", 1e-4)),
            beta=float(cfg.get("beta", 0.75)), name=cfg.get("name"))

    _map_localresponsenormalization = _map_lrn

    # ---- shape ops ----
    def _map_reshape(self, cfg):
        return ReshapeLayer(target_shape=tuple(cfg.get("target_shape", ())),
                            name=cfg.get("name"))

    def _map_permute(self, cfg):
        return PermuteLayer(dims=tuple(cfg.get("dims", ())),
                            name=cfg.get("name"))

    def _map_poolhelper(self, cfg):
        # custom layer in Theano-era GoogLeNet files (reference KerasPoolHelper)
        return PoolHelperLayer(name=cfg.get("name"))

    def _map_zeropadding1d(self, cfg):
        pad = cfg.get("padding", 1)
        if isinstance(pad, (list, tuple)):
            pad = tuple(int(p) for p in pad)
        return ZeroPadding1DLayer(pad=pad, name=cfg.get("name"))

    def _map_upsampling1d(self, cfg):
        s = cfg.get("size", cfg.get("length", 2))
        return Upsampling1D(size=int(s[0] if isinstance(s, (list, tuple)) else s),
                            name=cfg.get("name"))

    # ---- dilated + separable conv ----
    # Keras 1 Atrous* classes: dilation comes from atrous_rate, which
    # the base conv mappers already read
    _map_atrousconvolution2d = _map_conv2d
    _map_atrousconvolution1d = _map_conv1d

    def _map_separableconv2d(self, cfg):
        kernel = _pair(cfg.get("kernel_size",
                               (cfg.get("nb_row"), cfg.get("nb_col"))
                               if cfg.get("nb_row") else None), (3, 3))
        d = cfg.get("dilation_rate", (1, 1))
        return SeparableConvolution2D(
            n_out=int(cfg.get("filters", cfg.get("nb_filter", 0))),
            kernel_size=kernel,
            stride=_pair(cfg.get("strides", cfg.get("subsample"))),
            dilation=_pair(d),
            depth_multiplier=int(cfg.get("depth_multiplier", 1)),
            convolution_mode=_conv_mode(cfg),
            activation=_act(cfg.get("activation")),
            has_bias=cfg.get("use_bias", cfg.get("bias", True)),
            name=cfg.get("name"))

    _map_separableconvolution2d = _map_separableconv2d  # Keras 1 name


# Keras loss identifier → framework loss name (KerasLoss.java mapping).
_KERAS_LOSSES = {
    "categorical_crossentropy": "mcxent",
    "sparse_categorical_crossentropy": "mcxent",
    "binary_crossentropy": "xent",
    "mean_squared_error": "mse", "mse": "mse",
    "mean_absolute_error": "mae", "mae": "mae",
    "mean_squared_logarithmic_error": "msle", "msle": "msle",
    "kullback_leibler_divergence": "kl_divergence", "kld": "kl_divergence",
    "kl_divergence": "kl_divergence", "kldivergence": "kl_divergence",
    "poisson": "poisson",
    "cosine_similarity": "cosine_proximity",
    "cosine_proximity": "cosine_proximity",
    "hinge": "hinge", "squared_hinge": "squaredhinge",
}


def _updater_from_training_config(tc: dict):
    """Keras optimizer_config → framework updater (KerasModel's
    optimizer import role). Unknown optimizers fall back to Adam."""
    from deeplearning4j_tpu.common.updaters import (
        AdaGrad, Adam, Nesterovs, RmsProp, Sgd,
    )
    oc = tc.get("optimizer_config") or {}
    cname = oc.get("class_name", "")
    cfg = oc.get("config", {})
    lr = float(cfg.get("learning_rate", cfg.get("lr", 1e-3)))
    if cname in ("SGD", "Sgd"):
        mom = float(cfg.get("momentum", 0.0))
        return Nesterovs(lr, momentum=mom) if mom else Sgd(lr)
    if cname in ("RMSprop", "RMSProp"):
        return RmsProp(lr, rho=float(cfg.get("rho", 0.9)))
    if cname == "Adagrad":
        return AdaGrad(lr)
    if cname == "Adam":
        return Adam(lr, beta1=float(cfg.get("beta_1", 0.9)),
                    beta2=float(cfg.get("beta_2", 0.999)))
    return Adam(lr)


class KerasModelImport:
    """Entry points mirroring `KerasModelImport.java`."""

    # ------------------------------------------------------------ public
    @staticmethod
    def import_keras_model_and_weights(path, enforce_training_config=False):
        with Hdf5Archive(path) as h5:
            config = h5.read_attr_string("model_config")
            if config is None:
                raise ValueError(f"{path}: no model_config attribute")
            model_dict = json.loads(config)
            tc_str = h5.read_attr_string("training_config")
            training_config = json.loads(tc_str) if tc_str else None
            if (enforce_training_config and training_config is None):
                raise ValueError(
                    f"{path}: model was saved uncompiled (no "
                    f"training_config) but enforce_training_config=True")
            if model_dict.get("class_name") == "Sequential":
                return KerasModelImport._import_sequential(
                    model_dict, h5, training_config)
            return KerasModelImport._import_functional(
                model_dict, h5, training_config)

    @staticmethod
    def import_keras_sequential_model_and_weights(path, **kw):
        model = KerasModelImport.import_keras_model_and_weights(path, **kw)
        if not isinstance(model, MultiLayerNetwork):
            raise ValueError("Not a Sequential model")
        return model

    @staticmethod
    def import_keras_configuration(path):
        """Architecture only, no weights (reference
        `importKerasModelConfiguration` / `importKerasSequentialConfiguration`,
        `KerasModelImport.java:50-194`): accepts a bare `model.to_json()`
        architecture file or an .h5 whose `model_config` attribute is
        read — returns the mapped configuration object."""
        with open(path, "rb") as f:
            magic = f.read(8)
        if magic == b"\x89HDF\r\n\x1a\n":
            with Hdf5Archive(path) as h5:
                config = h5.read_attr_string("model_config")
                if config is None:
                    raise ValueError(f"{path}: no model_config attribute")
                model_dict = json.loads(config)
        else:
            with open(path, "r", errors="replace") as f:
                model_dict = json.loads(f.read())
        return KerasModelImport.config_from_dict(model_dict)

    @staticmethod
    def import_architecture_and_weights(arch, weights_path):
        """Architecture JSON (file path or dict) + a separate
        weights-only .h5 (the keras-applications distribution split:
        `model.to_json()` beside `save_weights` output). Weight copy is
        BY KERAS LAYER NAME, so it is robust to the file's layer order.
        Reference: `KerasModelImport.importKerasModelAndWeights(
        modelJsonFilename, weightsHdf5Filename)` overload
        (`KerasModelImport.java:103-140`)."""
        if isinstance(arch, (str, bytes)) or hasattr(arch, "__fspath__"):
            with open(arch, "r") as f:
                model_dict = json.loads(f.read())
        else:
            model_dict = arch
        with Hdf5Archive(weights_path) as h5:
            if model_dict.get("class_name") == "Sequential":
                return KerasModelImport._import_sequential(model_dict, h5)
            return KerasModelImport._import_functional(model_dict, h5)

    @staticmethod
    def config_from_dict(model_dict, training_config=None):
        """Keras architecture dict → our configuration object (the
        config-only half of the import: same layer mapping, no weight
        copy)."""
        if model_dict.get("class_name") == "Sequential":
            net = KerasModelImport._import_sequential(
                model_dict, None, training_config)
        else:
            net = KerasModelImport._import_functional(
                model_dict, None, training_config)
        return net.conf

    # -------------------------------------------------------- sequential
    @staticmethod
    def _layer_list(model_dict):
        cfg = model_dict["config"]
        if isinstance(cfg, dict):   # Keras 2.2+: {"name":..., "layers":[...]}
            return cfg["layers"]
        return cfg                   # Keras 1 / early 2: [...]

    @staticmethod
    def _input_type_from(layer_cfgs):
        first = layer_cfgs[0]["config"]
        # Keras 1/2: batch_input_shape; Keras 3 InputLayer: batch_shape
        shape = first.get("batch_input_shape", first.get("batch_shape"))
        if shape is not None:
            dims = [d for d in shape[1:]]
            if len(dims) == 3:   # [H, W, C] (channels_last)
                return InputType.convolutional(dims[0], dims[1], dims[2])
            if len(dims) == 2:   # [T, F]
                return InputType.recurrent(dims[1], dims[0])
            if len(dims) == 1:
                return InputType.feed_forward(dims[0])
        if "input_dim" in first and first.get("input_length"):
            return InputType.recurrent(first["input_dim"], first["input_length"])
        if "input_dim" in first:
            return InputType.feed_forward(first["input_dim"])
        raise ValueError("Cannot infer input shape from Keras config")

    @staticmethod
    def _channels_last(model_dict, h5) -> bool:
        """TF-backend Keras flattens NHWC; Theano-era (Keras 1) files
        flatten channel-major (the reference's dim-ordering handling,
        `KerasLayer.java` dimOrder). Priority: explicit per-layer
        dim_ordering (Keras 1 stores "th"/"tf") > backend attr >
        config-shape heuristic (Keras 1 Sequential config is a list)."""
        cfg = model_dict.get("config")
        layer_list = cfg.get("layers", []) if isinstance(cfg, dict) else cfg
        for lc in layer_list or []:
            ordering = (lc.get("config") or {}).get("dim_ordering")
            if ordering in ("th", "tf"):
                return ordering == "tf"
        backend = h5.read_attr_string("backend") if h5 is not None else None
        if backend:
            return backend == "tensorflow"
        return (model_dict.get("class_name") != "Sequential"
                or isinstance(cfg, dict))

    @staticmethod
    def _fix_flatten_order(preprocessors, channels_last: bool):
        from deeplearning4j_tpu.nn.conf.preprocessors import (
            CnnToFeedForwardPreProcessor,
        )
        if channels_last:
            for pp in preprocessors:
                if isinstance(pp, CnnToFeedForwardPreProcessor):
                    pp.data_format = "nhwc"

    @staticmethod
    def _loss_name(training_config) -> Optional[str]:
        if not training_config:
            return None
        loss = training_config.get("loss")
        if isinstance(loss, (list, tuple)) and loss:
            loss = loss[0]
        elif (isinstance(loss, dict) and loss
              and "class_name" not in loss):  # {output_name: loss} map
            loss = next(iter(loss.values()))
        if isinstance(loss, (list, tuple)) and loss:
            loss = loss[0]
        if isinstance(loss, dict):  # serialized loss object
            loss = (loss.get("config") or {}).get("name") or loss.get("class_name")
        if not isinstance(loss, str):
            return None
        # normalize CamelCase class names → snake identifiers
        # (CategoricalCrossentropy → categorical_crossentropy)
        key = loss.lower()
        if key not in _KERAS_LOSSES:
            key = re.sub(r"(?<=[a-z0-9])(?=[A-Z])", "_", loss).lower()
        return _KERAS_LOSSES.get(key)

    @staticmethod
    def _to_output_layer(dense, loss) -> "OutputLayer":
        return OutputLayer(n_out=dense.n_out, activation=dense.activation,
                           has_bias=dense.has_bias, name=dense.name,
                           loss=loss)

    @staticmethod
    def _import_sequential(model_dict, h5,
                           training_config=None) -> MultiLayerNetwork:
        layer_cfgs = KerasModelImport._layer_list(model_dict)
        mapper = KerasLayerMapper()
        updater = (_updater_from_training_config(training_config)
                   if training_config else Adam(1e-3))
        loss = KerasModelImport._loss_name(training_config)
        keras_names: List[Tuple[str, int]] = []  # (keras layer name, our idx)
        mapped_all: List = []
        for lc in layer_cfgs:
            cname = lc["class_name"]
            if cname == "InputLayer":
                continue
            mapped = mapper.map(cname, lc["config"])
            for mi, layer in enumerate(mapped):
                if mi == 0 and layer.__class__.__name__ != "LastTimeStep":
                    keras_names.append((lc["config"].get("name", cname),
                                        len(mapped_all)))
                mapped_all.append(layer)
        # A compiled Keras model carries its loss in training_config; a
        # trailing Dense becomes an OutputLayer so the import can fit()
        # (KerasModel attaches KerasLoss the same way).
        if loss is not None and mapped_all:
            if mapped_all[-1].__class__.__name__ == "DenseLayer":
                mapped_all[-1] = KerasModelImport._to_output_layer(
                    mapped_all[-1], loss)
            else:
                mapped_all.append(LossLayer(loss=loss))
        builder = (NeuralNetConfiguration.builder().updater(updater).list())
        for layer in mapped_all:
            builder.layer(layer)
        builder.set_input_type(KerasModelImport._input_type_from(layer_cfgs))
        conf = builder.build()
        KerasModelImport._fix_flatten_order(
            conf.input_preprocessors.values(),
            KerasModelImport._channels_last(model_dict, h5))
        net = MultiLayerNetwork(conf).init()
        if h5 is not None:
            KerasModelImport._copy_weights_mln(net, h5, keras_names)
        return net

    # -------------------------------------------------------- functional
    @staticmethod
    def _boundary_names(spec) -> List[str]:
        """input_layers/output_layers → layer names. Formats:
        Keras 1/2: [["name", 0, 0], ...]; Keras 3 single-tensor models
        flatten to one ["name", 0, 0]; plain strings pass through."""
        if not spec:
            return []
        if (isinstance(spec, list) and spec
                and isinstance(spec[0], str)
                and any(isinstance(x, int) for x in spec)):
            return [spec[0]]  # flat ["name", 0, 0]
        return [l[0] if isinstance(l, list) else l for l in spec]

    @staticmethod
    def _inbound_sources(inbound) -> List[str]:
        """First inbound node → source layer names. Formats:
        Keras 1/2: [[["src", 0, 0, {}], ...]]; Keras 3:
        [{"args": [<__keras_tensor__> | [<__keras_tensor__>, ...]],
          "kwargs": {}}] with keras_history carrying the source name."""
        if not inbound:
            return []
        node = inbound[0]
        entries = node if isinstance(node, list) else node.get("args", [])
        srcs: List[str] = []

        def walk(e):
            if isinstance(e, dict):
                if e.get("class_name") == "__keras_tensor__":
                    srcs.append(e["config"]["keras_history"][0])
            elif isinstance(e, list):
                if e and isinstance(e[0], str):
                    srcs.append(e[0])      # ["src", 0, 0, {...}]
                else:
                    for x in e:
                        walk(x)
            elif isinstance(e, str):
                srcs.append(e)
        for e in entries:
            walk(e)
        return srcs

    @staticmethod
    def _import_functional(model_dict, h5,
                           training_config=None) -> ComputationGraph:
        cfg = model_dict["config"]
        layer_cfgs = cfg["layers"]
        mapper = KerasLayerMapper()
        updater = (_updater_from_training_config(training_config)
                   if training_config else Adam(1e-3))
        loss = KerasModelImport._loss_name(training_config)
        builder = NeuralNetConfiguration.builder().updater(updater)
        g = ComputationGraphConfiguration.graph_builder(builder)
        input_names = KerasModelImport._boundary_names(cfg.get("input_layers", []))
        output_names = KerasModelImport._boundary_names(cfg.get("output_layers", []))
        g.add_inputs(*[n for n in input_names])
        input_types = []
        keras_names: List[Tuple[str, str]] = []
        alias: Dict[str, str] = {}  # keras layer name → node producing its output
        for lc in layer_cfgs:
            cname = lc["class_name"]
            name = lc.get("name", lc["config"].get("name"))
            srcs = KerasModelImport._inbound_sources(lc.get("inbound_nodes", []))
            srcs = [alias.get(s, s) for s in srcs]
            if cname == "InputLayer":
                shape = lc["config"].get("batch_input_shape",
                                         lc["config"].get("batch_shape"))
                dims = shape[1:]
                if len(dims) == 3:
                    input_types.append(InputType.convolutional(*dims))
                elif len(dims) == 2:
                    input_types.append(InputType.recurrent(dims[1], dims[0]))
                else:
                    input_types.append(InputType.feed_forward(dims[0]))
                alias[name] = name
                continue
            if cname == "Add" or (cname == "Merge" and
                                  lc["config"].get("mode", "sum") in ("sum", None)):
                g.add_vertex(name, ElementWiseVertex(op="add"), *srcs)
                alias[name] = name
                continue
            if cname == "Concatenate" or (cname == "Merge" and
                                          lc["config"].get("mode") == "concat"):
                g.add_vertex(name, MergeVertex(), *srcs)
                alias[name] = name
                continue
            mapped = mapper.map(cname, lc["config"])
            if not mapped:  # Flatten/Masking: pass-through to the source
                alias[name] = srcs[0]
                continue
            if (loss is not None and name in output_names
                    and mapped[-1].__class__.__name__ == "DenseLayer"):
                mapped[-1] = KerasModelImport._to_output_layer(
                    mapped[-1], loss)
            prev = srcs
            for mi, layer in enumerate(mapped):
                lname = name if mi == 0 else f"{name}_{mi}"
                if mi == 0:
                    keras_names.append((name, lname))
                g.add_layer(lname, layer, *prev)
                prev = [lname]
            alias[name] = prev[0]  # downstream refs see the LAST mapped layer
        g.set_input_types(*input_types)
        g.set_outputs(*[alias.get(n, n) for n in output_names])
        conf = g.build()
        KerasModelImport._fix_flatten_order(
            [n.preprocessor for n in conf.nodes.values()
             if n.preprocessor is not None],
            KerasModelImport._channels_last(model_dict, h5))
        net = ComputationGraph(conf).init()
        if h5 is not None:
            KerasModelImport._copy_weights_graph(net, h5, keras_names)
        return net

    # ----------------------------------------------------- weights-only h5
    @staticmethod
    def load_weights_into(net, path):
        """Copy a weights-only Keras .h5 (model.save_weights output — no
        model_config attr; the keras-applications distribution format)
        into an already-built network.

        Keras stores layers in creation order under `layer_names`;
        weighted layers are matched IN ORDER against this network's
        weighted layers, with every tensor shape-checked (`_coerce`
        raises on any mismatch, so a topology drift fails loudly instead
        of silently corrupting params). Reference parallel:
        `KerasModelUtils.copyWeightsToModel:59`."""
        with Hdf5Archive(path) as h5:
            if h5.exists("/layers") and not h5.read_attr_strings("layer_names"):
                return KerasModelImport._load_weights_into_k3(net, h5, path)
            root = KerasModelImport._weights_root(h5)
            lnames = h5.read_attr_strings("layer_names", root) or []
            keras_weighted = []
            for ln in lnames:
                kw = KerasModelImport._layer_weights(h5, root, ln)
                if kw:
                    keras_weighted.append((ln, kw))
            ours = KerasModelImport._weighted_layers(net)
            if len(keras_weighted) != len(ours):
                raise ValueError(
                    f"{path}: {len(keras_weighted)} weighted Keras layers vs "
                    f"{len(ours)} in the target network — topologies differ")
            for (kname, kw), (key, layer) in zip(keras_weighted, ours):
                KerasModelImport._apply_weights(net, key, layer, kw, kname)
        return net

    # Positional var→semantic-name tables for the Keras 3 .weights.h5
    # layout (layers/<slug>/vars/<i>; order = keras layer.weights order).
    _K3_VAR_NAMES = {
        "DenseLayer": ("kernel", "bias"),
        "OutputLayer": ("kernel", "bias"),
        "ConvolutionLayer": ("kernel", "bias"),
        "Convolution1DLayer": ("kernel", "bias"),
        "SeparableConvolution2D": ("depthwise_kernel", "pointwise_kernel",
                                   "bias"),
        "EmbeddingLayer": ("embeddings",),
        "LSTM": ("kernel", "recurrent_kernel", "bias"),
        "GravesLSTM": ("kernel", "recurrent_kernel", "bias"),
        "SimpleRnn": ("kernel", "recurrent_kernel", "bias"),
        "BatchNormalization": ("gamma", "beta", "moving_mean",
                               "moving_variance"),
    }

    @staticmethod
    def _load_weights_into_k3(net, h5, path):
        """Keras 3 .weights.h5: datasets at layers/<slug>/vars/<i>, layer
        name stored as the vars-group `name` attr. Creation order is NOT
        tracked in the file, so layers are matched BY NAME (our imported
        nets keep Keras layer names)."""
        by_name: Dict[str, List[np.ndarray]] = {}
        for slug in h5.list_children("/layers"):
            vpath = f"/layers/{slug}/vars"
            if not h5.exists(vpath):
                continue
            idxs = sorted((c for c in h5.list_children(vpath)), key=int)
            if not idxs:
                continue
            lname = h5.read_attr_string("name", vpath) or slug
            by_name[lname] = [h5.read_dataset(f"{vpath}/{i}") for i in idxs]
        ours = KerasModelImport._weighted_layers(net)
        unmatched = [getattr(l, "name", None) for _, l in ours
                     if getattr(l, "name", None) not in by_name]
        if unmatched:
            raise ValueError(
                f"{path}: weighted layers {unmatched} have no same-named "
                f"entry in the file (stored: {sorted(by_name)}) — "
                f"topologies differ")
        for key, layer in ours:
            arrays = by_name[layer.name]
            names = KerasModelImport._K3_VAR_NAMES.get(layer.__class__.__name__)
            if names is None:
                raise ValueError(
                    f"{path}: no Keras-3 var-name table for "
                    f"{layer.__class__.__name__}")
            if len(arrays) != len(names):
                # Positional assignment is only safe when counts agree —
                # e.g. BatchNorm(scale=False) stores 3 vars, and zipping
                # those against the 4-name table would silently shift
                # every tensor into the wrong slot.
                raise ValueError(
                    f"{path}: layer {layer.name} stores {len(arrays)} "
                    f"variables but {layer.__class__.__name__} expects "
                    f"{len(names)} ({names}) — cannot match positionally")
            kw = dict(zip(names, arrays))
            KerasModelImport._apply_weights(net, key, layer, kw, layer.name)
        return net

    @staticmethod
    def _weighted_layers(net):
        """(params_key, layer) for every layer holding params, in
        network order — shared by both weights-only loaders."""
        if hasattr(net, "layers"):  # MultiLayerNetwork
            return [(str(i), l) for i, l in enumerate(net.layers)
                    if net.params.get(str(i))]
        return [(n, net.conf.nodes[n].layer)
                for n in net.conf.topo_order if net.params.get(n)]

    # ----------------------------------------------------------- weights
    @staticmethod
    def _weights_root(h5) -> str:
        return "/model_weights" if h5.exists("/model_weights") else "/"

    @staticmethod
    def _layer_weights(h5, root: str, lname: str) -> Dict[str, np.ndarray]:
        gpath = f"{root}/{lname}".replace("//", "/")
        names = h5.read_attr_strings("weight_names", gpath)
        out = {}
        for wn in names:
            short = wn.split("/")[-1].split(":")[0]
            # Keras 1 prefixes the layer name ("dense_1_W" → "W",
            # "lstm_1_W_i" → "W_i")
            if short.startswith(lname + "_"):
                short = short[len(lname) + 1:]
            out[short] = h5.read_dataset(f"{gpath}/{wn}".replace("//", "/"))
        return out

    @staticmethod
    def _convert(layer, kw: Dict[str, np.ndarray]) -> Tuple[Dict, Dict]:
        """Keras weights → (params, state) for one framework layer."""
        params, state = {}, {}
        cls = layer.__class__.__name__
        if cls in ("DenseLayer", "OutputLayer"):
            params["W"] = kw.get("kernel", kw.get("W"))
            if "bias" in kw or "b" in kw:
                params["b"] = kw.get("bias", kw.get("b"))
        elif cls in ("ConvolutionLayer", "Convolution1DLayer"):
            k = kw.get("kernel", kw.get("W"))
            if k is not None and k.ndim == 3:
                k = k[:, None, :, :]  # Keras Conv1D [k,in,out] → [k,1,in,out]
            params["W"] = k
            if "bias" in kw or "b" in kw:
                params["b"] = kw.get("bias", kw.get("b"))
        elif cls == "SeparableConvolution2D":
            params["dW"] = kw.get("depthwise_kernel")
            params["pW"] = kw.get("pointwise_kernel")
            if "bias" in kw or "b" in kw:
                params["b"] = kw.get("bias", kw.get("b"))
        elif cls == "EmbeddingLayer":
            params["W"] = kw.get("embeddings", kw.get("W"))
        elif cls in ("LSTM", "GravesLSTM"):
            K = kw.get("kernel"); R = kw.get("recurrent_kernel"); b = kw.get("bias")
            if K is None and "W_i" in kw:  # Keras 1 per-gate weights
                K = np.concatenate([kw["W_i"], kw["W_f"], kw["W_c"], kw["W_o"]], 1)
                R = np.concatenate([kw["U_i"], kw["U_f"], kw["U_c"], kw["U_o"]], 1)
                b = np.concatenate([kw["b_i"], kw["b_f"], kw["b_c"], kw["b_o"]])

            def ifco_to_ifog(a, axis):
                i, f, c, o = np.split(a, 4, axis=axis)
                return np.concatenate([i, f, o, c], axis=axis)
            params["W"] = ifco_to_ifog(K, 1)
            params["RW"] = ifco_to_ifog(R, 1)
            if b is not None:
                params["b"] = ifco_to_ifog(b, 0)
        elif cls == "SimpleRnn":
            params["W"] = kw.get("kernel", kw.get("W"))
            params["RW"] = kw.get("recurrent_kernel", kw.get("U"))
            if "bias" in kw or "b" in kw:
                params["b"] = kw.get("bias", kw.get("b"))
        elif cls == "BatchNormalization":
            if "gamma" in kw:
                params["gamma"] = kw["gamma"]
            if "beta" in kw:
                params["beta"] = kw["beta"]
            if "moving_mean" in kw:
                state["mean"] = kw["moving_mean"]
            if "moving_variance" in kw:
                state["var"] = kw["moving_variance"]
        return params, state

    @staticmethod
    def _coerce(arr: np.ndarray, expect, kname: str, pn: str) -> np.ndarray:
        """Shape-check against the initialized param; a 4-D mismatch that
        matches after OIHW→HWIO transpose is a Theano-dialect kernel
        (`KerasConvolution.java` dim-ordering handling) — transpose +
        180° spatial flip."""
        expect = tuple(expect)
        if tuple(arr.shape) == expect:
            return arr
        if arr.ndim == 4 and np.transpose(arr, (2, 3, 1, 0)).shape == expect:
            return np.ascontiguousarray(np.transpose(arr, (2, 3, 1, 0))[::-1, ::-1])
        raise ValueError(f"layer {kname} param {pn}: {arr.shape} != {expect}")

    @staticmethod
    def _apply_weights(net, params_key, layer, kw, kname):
        params, state = KerasModelImport._convert(layer, kw)
        missing = [pn for pn, arr in params.items() if arr is None]
        if missing:
            raise ValueError(
                f"layer {kname}: could not match Keras weights for "
                f"{missing}; stored weight names were {sorted(kw)}")
        for pn, arr in params.items():
            arr = KerasModelImport._coerce(np.asarray(arr),
                                           net.params[params_key][pn].shape,
                                           kname, pn)
            net.params[params_key][pn] = np.asarray(arr, np.float32)
        for sn, arr in state.items():
            net.net_state[params_key][sn] = np.asarray(arr, np.float32)

    @staticmethod
    def _copy_weights_mln(net, h5, keras_names):
        root = KerasModelImport._weights_root(h5)
        for kname, idx in keras_names:
            kw = KerasModelImport._layer_weights(h5, root, kname)
            if kw:
                KerasModelImport._apply_weights(net, str(idx), net.layers[idx],
                                                kw, kname)

    @staticmethod
    def _copy_weights_graph(net, h5, keras_names):
        root = KerasModelImport._weights_root(h5)
        for kname, our_name in keras_names:
            kw = KerasModelImport._layer_weights(h5, root, kname)
            if kw:
                KerasModelImport._apply_weights(
                    net, our_name, net.conf.nodes[our_name].layer, kw, kname)
