"""Multi-tenant continuous learning: LoRA adapter deltas over a
shared base model, per-tenant train→publish→swap.

`lora` owns the adapter math and the `LoRAWeight` pytree node;
`fleet` owns `TenantFleet`, the shared-base serving host. The publish
unit is the adapter tree alone (kilobytes) — `ModelRegistry.
publish_adapter` / `resolve_adapter` in serving/registry.py.
"""

from deeplearning4j_tpu.tenancy.lora import (  # noqa: F401
    LoRAWeight, adapter_weight_keys, init_adapter, attach_adapter,
    extract_adapter, strip_adapter, compose_params, adapter_bytes,
    save_adapter, load_adapter, contains_lora,
)
from deeplearning4j_tpu.tenancy.fleet import TenantFleet  # noqa: F401
