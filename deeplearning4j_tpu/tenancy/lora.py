"""LoRA-style low-rank adapters over a frozen shared base.

The publish unit of the multi-tenant fleet is an adapter DELTA, not a
model: each tenant fine-tunes `W_eff = W + (alpha/r) * B @ A` with the
base `W` frozen, then ships only `{B, A}` (kilobytes against a model
of megabytes). N tenants then serve from ONE in-memory copy of the
base params — composition happens inside the matmul, never by
materializing `W_eff`:

    x @ W_eff = x @ W + ((x @ B) @ A) * (alpha/r)

so the low-rank factors ride the dispatch as two skinny matmuls and
the base weight stays shared by reference (and may itself be an int8
`QuantizedTensor` — the recursion through `nd.quant.matmul` makes
int8-base + fp-adapter compose for free).

The `LoRAWeight` pytree node wraps a weight leaf the layer declared
via `Layer.adapter_weights()` (the `quantizable_weights()` mirror —
same matmul seams). jit/tree_map/donation see ordinary leaves; the
layer code never changes. `frozen` rides the node as STATIC aux data:
the matmul stops gradients at the base read, so a `fit()` on an
adapted net differentiates only the adapter leaves and the base stays
bit-identical (`nn/multilayer._apply_updates` keeps the base leaf's
object identity — no `-0.0` churn, no per-tenant base copy).

Init follows the LoRA convention: `A ~ N(0, 1/r)` and `B = 0`, so a
freshly attached adapter is an EXACT no-op (x @ B is zeros) — the
adapter-on/off parity tests pin that down.

Honest limits: adapted layers must not carry l1/l2 regularization or
norm constraints (both would touch the wrapped node as if it were an
array — and l1/l2 would push nonzero gradient into a frozen base);
`attach_adapter` refuses them. Embedding tables don't participate
(gather path, no matmul seam).
"""

from __future__ import annotations

import io
import json
import zipfile
from pathlib import Path
from typing import Dict, Optional, Union

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.nd import quant

ADAPTER_FORMAT_VERSION = 1


class LoRAWeight:
    """A matmul weight with a low-rank delta: children `(base, B, A)`
    — `base` is the shared (possibly int8-quantized) weight, `B`
    `[n_in, r]`, `A` `[r, n_out]` — and static aux `(scale, frozen)`
    with `scale = alpha / r`."""

    __slots__ = ("base", "B", "A", "scale", "frozen")

    def __init__(self, base, B, A, scale: float, frozen: bool = True):
        self.base = base
        self.B = B
        self.A = A
        self.scale = float(scale)
        self.frozen = bool(frozen)

    # array-ish surface (shape checks, width validation)
    @property
    def shape(self):
        return self.base.shape

    @property
    def ndim(self):
        return self.base.ndim

    @property
    def dtype(self):
        return self.base.dtype

    def __repr__(self):
        return (f"LoRAWeight(shape={tuple(self.shape)}, "
                f"r={self.B.shape[-1]}, scale={self.scale}, "
                f"frozen={self.frozen})")


def _lw_flatten(w):
    return (w.base, w.B, w.A), (w.scale, w.frozen)


def _lw_unflatten(aux, children):
    base, B, A = children
    scale, frozen = aux
    return LoRAWeight(base, B, A, scale, frozen)


jax.tree_util.register_pytree_node(LoRAWeight, _lw_flatten, _lw_unflatten)


def _lora_matmul(x, w: LoRAWeight):
    """`x @ W_eff` without materializing `W_eff`: base matmul (through
    `quant.matmul`, so an int8 base dequantizes inside as usual) plus
    the rank-r bottleneck. `stop_gradient` on a frozen base makes its
    cotangent exactly zero — the updater never moves it."""
    base = w.base
    if w.frozen:
        base = jax.tree_util.tree_map(jax.lax.stop_gradient, base)
    y = quant.matmul(x, base)
    delta = (x @ w.B.astype(x.dtype)) @ w.A.astype(x.dtype)
    return y + delta * jnp.asarray(w.scale, x.dtype)


quant.register_matmul_extension(LoRAWeight, _lora_matmul)


# ------------------------------------------------------------ tree helpers
def adapter_weight_keys(net) -> Dict[str, list]:
    """{layer_key: [param_key, ...]} of every weight the net's layers
    declare adapter-eligible (`Layer.adapter_weights()`)."""
    out = {}
    for i, layer in enumerate(net.layers):
        keys = [k for k in layer.adapter_weights()
                if k in net.params.get(str(i), {})]
        if keys:
            out[str(i)] = keys
    return out


def contains_lora(tree) -> bool:
    """True if any node in `tree` is a LoRAWeight (checked on the
    container structure, so it works on traced trees too)."""
    if isinstance(tree, LoRAWeight):
        return True
    if isinstance(tree, dict):
        return any(contains_lora(v) for v in tree.values())
    if isinstance(tree, (list, tuple)):
        return any(contains_lora(v) for v in tree)
    return False


def _leaf_shape(w):
    # a quantized base reports its original weight shape
    return tuple(w.shape)


def init_adapter(net, *, rank: int, seed: int = 0) -> dict:
    """A fresh adapter tree `{lk: {pk: {"B", "A"}}}` for every
    adapter-eligible weight: `B` zeros `[n_in, r]`, `A` gaussian
    `N(0, 1/r)` `[r, n_out]` — the composed delta starts exactly 0."""
    if rank < 1:
        raise ValueError(f"adapter rank must be >= 1; got {rank}")
    plan = adapter_weight_keys(net)
    root = jax.random.PRNGKey(seed)
    out: dict = {}
    for lk, keys in plan.items():
        lp = {}
        for j, pk in enumerate(sorted(keys)):
            w = net.params[lk][pk]
            n_in, n_out = _leaf_shape(w)[-2], _leaf_shape(w)[-1]
            key = jax.random.fold_in(jax.random.fold_in(root, int(lk)), j)
            lp[pk] = {
                "B": jnp.zeros((n_in, rank), jnp.float32),
                "A": (jax.random.normal(key, (rank, n_out), jnp.float32)
                      / float(rank)),
            }
        out[lk] = lp
    return out


def _check_layer_adaptable(layer, lk):
    if layer.l1 or layer.l2:
        raise ValueError(
            f"layer {lk}: l1/l2 regularization on an adapted layer "
            f"would touch the wrapped LoRAWeight node (and push "
            f"gradient into a frozen base) — set l1=l2=0 on adapted "
            f"layers")
    if layer.constraints:
        raise ValueError(
            f"layer {lk}: norm constraints are not supported on "
            f"adapted layers (they rescale the raw param leaf, which "
            f"is now a LoRAWeight node)")


def attach_adapter(net, adapter: dict, *, rank: int, alpha: float,
                   frozen: bool = True):
    """Wrap the net's adapter-eligible weights as `LoRAWeight` nodes
    (training-side composition). Reassigns `net.params` — a NEW tree
    object, so the `quant.serving_params` identity cache invalidates,
    exactly like fit()/restore — and patches `updater_state` so the
    adapter leaves get fresh optimizer slots ({"B": ..., "A": ...}
    dicts; a frozen base keeps no slot — it will never move).
    Base leaves are shared BY REFERENCE: attaching N adapters to one
    base allocates only the B/A factors."""
    scale = float(alpha) / float(rank)
    new_params = {lk: dict(lv) for lk, lv in net.params.items()}
    new_upd = {lk: dict(lv) for lk, lv in net.updater_state.items()}
    from deeplearning4j_tpu.common.updaters import Sgd
    for lk, lv in adapter.items():
        layer = net.layers[int(lk)]
        _check_layer_adaptable(layer, lk)
        updater = layer.updater or Sgd(1e-3)
        for pk, ba in lv.items():
            w = new_params[lk][pk]
            if isinstance(w, LoRAWeight):
                raise ValueError(
                    f"layer {lk} param {pk} already carries an "
                    f"adapter — strip_adapter() first")
            B, A = jnp.asarray(ba["B"]), jnp.asarray(ba["A"])
            if (B.shape[0], A.shape[1]) != (_leaf_shape(w)[-2],
                                            _leaf_shape(w)[-1]):
                raise ValueError(
                    f"layer {lk} param {pk}: adapter factors "
                    f"{B.shape}x{A.shape} don't fit weight "
                    f"{tuple(w.shape)}")
            new_params[lk][pk] = LoRAWeight(w, B, A, scale, frozen)
            slots = {"B": updater.init_state(B),
                     "A": updater.init_state(A)}
            if not frozen:
                slots["base"] = updater.init_state(w)
            new_upd.setdefault(lk, {})[pk] = slots
    net.params = new_params
    net.updater_state = new_upd
    return net


def extract_adapter(net) -> dict:
    """The adapter tree `{lk: {pk: {"B", "A"}}}` currently attached —
    the publish unit (`ModelRegistry.publish_adapter`)."""
    out: dict = {}
    for lk, lv in net.params.items():
        for pk, w in lv.items():
            if isinstance(w, LoRAWeight):
                out.setdefault(lk, {})[pk] = {"B": w.B, "A": w.A}
    return out


def strip_adapter(net) -> dict:
    """Detach: restore plain base leaves (same objects that went in)
    and return the adapter tree. Reassigns `net.params` (identity
    invalidation) and drops the adapter optimizer slots."""
    adapter: dict = {}
    new_params = {lk: dict(lv) for lk, lv in net.params.items()}
    new_upd = {lk: dict(lv) for lk, lv in net.updater_state.items()}
    from deeplearning4j_tpu.common.updaters import Sgd
    for lk, lv in list(new_params.items()):
        for pk, w in list(lv.items()):
            if isinstance(w, LoRAWeight):
                adapter.setdefault(lk, {})[pk] = {"B": w.B, "A": w.A}
                lv[pk] = w.base
                layer = net.layers[int(lk)]
                updater = layer.updater or Sgd(1e-3)
                new_upd[lk][pk] = updater.init_state(w.base) \
                    if not isinstance(w.base, quant.QuantizedTensor) \
                    else new_upd[lk].get(pk)
    net.params = new_params
    net.updater_state = new_upd
    return adapter


def compose_params(base_params: dict, adapter: dict, *, rank: int,
                   alpha: float) -> dict:
    """Serving-side composition: a params tree whose adapted leaves
    are `LoRAWeight(base, B, A)` nodes SHARING the base leaves by
    reference (the base may already be the int8-quantized serving
    copy). Non-adapted leaves are shared verbatim — composing a tenant
    view allocates nothing but the tree spine."""
    scale = float(alpha) / float(rank)
    out = {}
    for lk, lv in base_params.items():
        lav = adapter.get(lk, {})
        out[lk] = {pk: (LoRAWeight(w, jnp.asarray(lav[pk]["B"]),
                                   jnp.asarray(lav[pk]["A"]), scale, True)
                        if pk in lav else w)
                   for pk, w in lv.items()}
    return out


def apply_adapter_update(updater, p: LoRAWeight, g, slots: dict, step):
    """One optimizer step on a LoRAWeight leaf (the
    `_apply_updates` branch): B/A move through the layer's updater;
    a frozen base keeps its OBJECT IDENTITY (not `base - 0.0`), so
    the shared-base memory claim and bit-identity both hold."""
    dB, sB = updater.apply(g.B.astype(p.B.dtype), slots["B"], step)
    dA, sA = updater.apply(g.A.astype(p.A.dtype), slots["A"], step)
    new_B = p.B - dB.astype(p.B.dtype)
    new_A = p.A - dA.astype(p.A.dtype)
    new_slots = dict(slots, B=sB, A=sA)
    if p.frozen or "base" not in slots:
        base = p.base
    else:
        db, sb = updater.apply(g.base.astype(p.base.dtype),
                               slots["base"], step)
        base = p.base - db.astype(p.base.dtype)
        new_slots["base"] = sb
    return LoRAWeight(base, new_B, new_A, p.scale, p.frozen), new_slots


def adapter_bytes(adapter: dict) -> int:
    """Bytes of the adapter tree — the <5%-of-full-zip evidence input."""
    return quant.weight_bytes(adapter)


# ------------------------------------------------------------------ serde
from deeplearning4j_tpu.fault.state import checksum_array as _crc


def save_adapter(path: Union[str, Path, io.IOBase], adapter: dict, *,
                 meta: Optional[dict] = None):
    """Adapter artifact: a zip holding `adapter.npz` ("lk::pk__B"
    keys) + `meta.json` (format version, rank/alpha/base_version from
    `meta`, per-array crc32) — the ModelSerializer container idiom at
    adapter scale."""
    flat = {}
    for lk, lv in adapter.items():
        for pk, ba in lv.items():
            flat[f"{lk}::{pk}__B"] = np.asarray(ba["B"])
            flat[f"{lk}::{pk}__A"] = np.asarray(ba["A"])
    checksums = {k: _crc(arr) for k, arr in flat.items()}
    m = dict(meta or {})
    m.setdefault("format_version", ADAPTER_FORMAT_VERSION)
    m["array_checksums"] = checksums
    buf = io.BytesIO()
    np.savez(buf, **flat)
    if hasattr(path, "write"):
        zf_target = path
    else:
        zf_target = str(path)
    with zipfile.ZipFile(zf_target, "w", zipfile.ZIP_DEFLATED) as zf:
        zf.writestr("adapter.npz", buf.getvalue())
        zf.writestr("meta.json", json.dumps(m, indent=2))


def load_adapter(path: Union[str, Path, io.IOBase]):
    """-> (adapter_tree, meta). Verifies per-array crc32 when the
    artifact carries checksums; raises ValueError on corruption."""
    src = path if hasattr(path, "read") else str(path)
    with zipfile.ZipFile(src, "r") as zf:
        meta = json.loads(zf.read("meta.json"))
        with zf.open("adapter.npz") as f:
            data = np.load(io.BytesIO(f.read()))
            flat = {k: data[k] for k in data.files}
    expected = meta.get("array_checksums") or {}
    bad = [k for k, arr in flat.items()
           if k in expected and _crc(arr) != expected[k]]
    if bad:
        raise ValueError(
            f"adapter artifact failed checksum verification: {bad[:5]}")
    out: dict = {}
    for key, arr in flat.items():
        lp, slot = key.rsplit("__", 1)
        lk, pk = lp.split("::", 1)
        out.setdefault(lk, {}).setdefault(pk, {})[slot] = jnp.asarray(arr)
    return out, meta
