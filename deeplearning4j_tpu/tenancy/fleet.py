"""TenantFleet — N tenants served from ONE in-memory base model.

The multi-tenant shape of the serving tier: every tenant is a
`FleetServer` deployment (own `GenerationServer`, own hot-swap lock,
own gauges), but what a deployment resolves is an ADAPTER version
from the per-tenant adapter store (`ModelRegistry.resolve_adapter`)
composed over a single shared base net held in this process:

- **One base copy.** The base model's params are resolved once at
  fleet construction (and pinned). Every tenant's serving params are
  `tenancy.lora.compose_params(base, adapter)` — `LoRAWeight` nodes
  whose `base` leaves are the SAME array objects across all tenants;
  composing a tenant allocates the rank-r factors and a tree spine,
  nothing else. With `quantize="int8"` the base is quantized ONCE
  (`quant.serving_params` on the base net) and tenants share the int8
  copy — int8 base + fp adapter, composed inside the matmul.
- **Composed-params cache.** Keyed on
  `(base version, adapter version, quantize mode)` and on the
  IDENTITY of the base net's params tree (the
  `quant.serving_params` invalidation pattern): a base fit()/restore
  reassigns that tree, so every tenant's next composition sees the
  fresh base instead of silently serving stale weights.
- **Per-tenant hot-swap = adapter pointer flip.** `swap(tenant)` is
  the inherited FleetServer discipline — warm the successor, flip,
  migrate queued, drain the incumbent — where "successor" differs
  from the incumbent only by its adapter factors. In-flight streams
  finish on the adapter version they started with (version-tagged
  parity, the PR-12 drain contract); retention can never collect a
  served adapter (`pin_adapter` before resolve, released through the
  `_release_version` seam).
"""

from __future__ import annotations

import logging
import threading
from typing import Optional

from deeplearning4j_tpu.serving.fleet import FleetServer
from deeplearning4j_tpu.serving.registry import ModelRegistry
from deeplearning4j_tpu.serving.server import GenerationServer
from deeplearning4j_tpu.nd import quant
from deeplearning4j_tpu.tenancy import lora

log = logging.getLogger("deeplearning4j_tpu.tenancy.fleet")


class _TenantNetView:
    """A per-tenant view of the shared base net: its OWN `params`
    (the composed tree) and its own `__dict__` (so nothing caches
    onto the base), everything else — conf, layers, net_state, dtype
    — delegated to the one base net. The engine treats it as an
    ordinary net."""

    def __init__(self, base_net, params):
        self._base_net = base_net
        self.params = params
        # the serving jit caches key on `net.__dict__` directly
        # (engine._shared_jit, zoo.transformer.get_prefill_bucketed),
        # which `__getattr__` delegation can't intercept — alias the
        # base net's cache dicts into this view so every tenant server
        # and every adapter-swap successor reuses ONE compile instead
        # of paying the full decode/prefill compile per flip
        for cache_attr in ("_serving_jit_cache", "_transformer_gen_jit"):
            self.__dict__[cache_attr] = base_net.__dict__.setdefault(
                cache_attr, {})

    def __getattr__(self, name):
        return getattr(self.__dict__["_base_net"], name)


class TenantFleet(FleetServer):
    """FleetServer whose deployment names are TENANTS of one shared
    base model: deploy/swap/scale/undeploy, gauges, drain discipline
    and the router interface (`has`/`active`/`names`) are all
    inherited — only what a "version" means (a per-tenant adapter
    version) and what a server is built from (composed shared-base
    params) change."""

    def __init__(self, registry: ModelRegistry, model: str, *,
                 base_version="latest", quantize: Optional[str] = None,
                 gauge_interval_s: float = 0.25):
        super().__init__(registry, gauge_interval_s=gauge_interval_s)
        self.model = model
        self.quantize = quantize
        target = (registry.latest(model) if base_version == "latest"
                  else int(base_version))
        if target is None:
            raise FileNotFoundError(
                f"no published versions of {model!r} to base a tenant "
                f"fleet on")
        registry.pin(model, target)
        try:
            self.base_net, self.base_version = registry.resolve(
                model, base_version)
            if self.base_version != target:
                registry.pin(model, self.base_version)
                registry.unpin(model, target)
        except Exception:
            registry.unpin(model, target)
            raise
        # {tenant: {"source": <base params identity>, "key": (base_v,
        #  adapter_v, mode), "tree": composed}} — one entry per tenant
        self._composed_cache: dict = {}
        self._compose_lock = threading.Lock()

    # ------------------------------------------------------- composition
    def composed_params(self, tenant: str, adapter: dict,
                        adapter_version: int, *, rank: int,
                        alpha: float, quantize: Optional[str] = None):
        """The tenant's serving params: shared (possibly int8) base +
        this adapter version, cached per tenant and invalidated when
        EITHER the key changes (new adapter/base version, different
        quantize mode) or the base net's params tree is reassigned
        (fit()/restore — the identity check)."""
        key = (self.base_version, int(adapter_version), quantize)
        base_src = self.base_net.params
        with self._compose_lock:
            ent = self._composed_cache.get(tenant)
            if (ent is not None and ent["source"] is base_src
                    and ent["key"] == key):
                return ent["tree"]
            base_tree = quant.serving_params(self.base_net, quantize)
            tree = lora.compose_params(base_tree, adapter, rank=rank,
                                       alpha=alpha)
            self._composed_cache[tenant] = {
                "source": base_src, "key": key, "tree": tree}
            return tree

    def shared_base_copies(self) -> int:
        """Distinct in-memory base-weight copies across every deployed
        tenant — the one-base-copy evidence probe. Every adapted
        leaf's `base` object must be an object of the base net's ONE
        serving tree; returns 1 when that holds, else 1 + the number
        of stray copies found."""
        stray = set()
        base_tree = quant.serving_params(self.base_net, self.quantize)
        base_ids = {id(w) for lv in base_tree.values()
                    for w in lv.values()}
        for tenant in self.names():
            server, _ = self.active(tenant)
            params = server.engine.net.params
            for lv in params.values():
                for w in lv.values():
                    if isinstance(w, lora.LoRAWeight) \
                            and id(w.base) not in base_ids:
                        stray.add(id(w.base))
        return 1 + len(stray)

    # ----------------------------------------------------------- versions
    def _release_version(self, tenant: str, version: int):
        self.registry.unpin_adapter(self.model, tenant, version)

    def _build_server(self, tenant: str, version, server_kw: dict,
                      warm_len, warm_tokens: int):
        """Resolve + compose + warm + start one tenant server. The
        target ADAPTER version is pinned before resolve (the
        FleetServer pin-before-resolve rule applied to the adapter
        store); pins taken here are released on failure."""
        reg = self.registry
        model = self.model
        target = (reg.latest_adapter(model, tenant)
                  if version == "latest" else int(version))
        if target is None:
            raise FileNotFoundError(
                f"no published adapters for {model!r} tenant "
                f"{tenant!r}")
        pinned_here = []

        def pin(v):
            reg.pin_adapter(model, tenant, v)
            pinned_here.append(v)

        pin(target)
        try:
            adapter, meta, v = reg.resolve_adapter(model, tenant,
                                                   version)
            if v != target:
                pin(v)
                reg.unpin_adapter(model, tenant, target)
                pinned_here.remove(target)
            server_kw = dict(server_kw)
            server_kw.setdefault("name", tenant)
            # quantization is a FLEET concern: the base quantizes once
            # and is shared, so the engine gets pre-composed params
            # and must not re-quantize per tenant
            qmode = server_kw.pop("quantize", self.quantize)
            params = self.composed_params(
                tenant, adapter, v, rank=int(meta["rank"]),
                alpha=float(meta["alpha"]), quantize=qmode)
            view = _TenantNetView(self.base_net, params)
            server = GenerationServer(view, **server_kw)
            with self._lock:
                prefixes = list(self._prefixes.get(tenant, ()))
            for ids in prefixes:
                server.register_prefix(ids)
            if warm_len is not None:
                server.warmup(int(warm_len), warm_tokens)
            server.start()
            return server, v
        except Exception:
            for v_ in pinned_here:
                reg.unpin_adapter(model, tenant, v_)
            raise

    # ----------------------------------------------------------- teardown
    def stop(self, *, drain: bool = False, drain_timeout: float = 600.0):
        try:
            super().stop(drain=drain, drain_timeout=drain_timeout)
        finally:
            self.registry.unpin(self.model, self.base_version)
