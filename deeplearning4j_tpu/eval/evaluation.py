"""Classification evaluation with confusion matrix.

Reference: `eval/Evaluation.java` (1,627 LoC): `eval()` accumulates a
confusion matrix from (labels, predictions); metrics: accuracy :1138,
precision :664, recall :803, f1 :1031, fBeta :998, gMeasure :1094,
falsePositiveRate :851, falseNegativeRate :913, falseAlarmRate :975,
matthewsCorrelation :1170, MACRO/MICRO averaging overloads
(EvaluationAveraging), per-class count maps :1218-1262, label-name-aware
stats() report :499-509 with warning surfacing, JSON serde
(`BaseEvaluation.toJson`), merge :1392. Time-series inputs are flattened
with mask support (`evalTimeSeries`). Binary decision threshold and
cost-array constructors :156-180.
"""

from __future__ import annotations

import json
from enum import Enum
from typing import Dict, List, Optional

import numpy as np


def check_payload_type(d: dict, expected: str):
    """Shared serde guard: every evaluator's JSON payload carries a
    type tag; reject mismatches with one consistent error."""
    if d.get("type") != expected:
        raise ValueError(f"Not a(n) {expected} payload: {d.get('type')!r}")


class EvaluationAveraging(str, Enum):
    MACRO = "macro"
    MICRO = "micro"


class ConfusionMatrix:
    def __init__(self, num_classes: int):
        self.matrix = np.zeros((num_classes, num_classes), dtype=np.int64)

    def add(self, actual: np.ndarray, predicted: np.ndarray):
        np.add.at(self.matrix, (actual, predicted), 1)

    def get_count(self, actual: int, predicted: int) -> int:
        return int(self.matrix[actual, predicted])

    def __str__(self):
        return str(self.matrix)


def _flatten_time_series(labels, preds, mask):
    """[B,T,C] → [B*T, C], dropping masked steps (reference
    evalTimeSeries + MaskedReductionUtil)."""
    labels = np.asarray(labels)
    preds = np.asarray(preds)
    if labels.ndim == 3:
        b, t, c = labels.shape
        labels = labels.reshape(b * t, c)
        preds = preds.reshape(b * t, c)
        if mask is not None:
            m = np.asarray(mask).reshape(-1).astype(bool)
            labels, preds = labels[m], preds[m]
    return labels, preds


class Evaluation:
    def __init__(self, num_classes: Optional[int] = None, top_n: int = 1,
                 labels_names: Optional[List[str]] = None,
                 binary_decision_threshold: Optional[float] = None,
                 cost_array: Optional[np.ndarray] = None):
        if isinstance(num_classes, (list, tuple)):  # Evaluation(labels) ctor
            labels_names, num_classes = list(num_classes), len(num_classes)
        self.num_classes = num_classes
        self.top_n = top_n
        self.labels_names = labels_names
        # reference ctors :156-180 — threshold for binary problems,
        # per-class cost multipliers applied before argmax
        self.binary_decision_threshold = binary_decision_threshold
        self.cost_array = (None if cost_array is None
                           else np.asarray(cost_array, np.float64))
        self.confusion: Optional[ConfusionMatrix] = None
        self.top_n_correct = 0
        self.total = 0
        # per-example Prediction tracking, populated only when eval() is
        # given record metadata (reference eval/meta/Prediction.java)
        from deeplearning4j_tpu.eval.meta import PredictionLedger
        self._ledger = PredictionLedger()

    def _ensure(self, c):
        if self.confusion is None:
            self.num_classes = self.num_classes or c
            self.confusion = ConfusionMatrix(self.num_classes)

    def reset(self):
        self.confusion = None
        self.top_n_correct = 0
        self.total = 0
        from deeplearning4j_tpu.eval.meta import PredictionLedger
        self._ledger = PredictionLedger()

    def eval(self, labels, predictions, mask=None, record_metadata=None):
        labels, predictions = _flatten_time_series(labels, predictions, mask)
        self._ensure(labels.shape[-1])
        actual = np.argmax(labels, axis=-1)
        if (self.binary_decision_threshold is not None
                and predictions.shape[-1] == 2):
            pred = (predictions[:, 1] >=
                    self.binary_decision_threshold).astype(np.int64)
        elif self.cost_array is not None:
            pred = np.argmax(predictions * self.cost_array[None, :], axis=-1)
        else:
            pred = np.argmax(predictions, axis=-1)
        if record_metadata is not None:
            # time-series flattening / masking can change the row count;
            # silently misaligned attribution would be worse than failing
            if len(record_metadata) != len(actual):
                raise ValueError(
                    f"record_metadata has {len(record_metadata)} entries but "
                    f"evaluation flattened/masked to {len(actual)} rows; "
                    "per-example metadata tracking supports 2-d labels (or "
                    "pre-flattened metadata aligned with kept rows)")
            self._ledger.record(actual, pred, record_metadata)
        self.confusion.add(actual, pred)
        self.total += len(actual)
        if self.top_n > 1:
            order = np.argsort(predictions, axis=-1)[:, ::-1][:, :self.top_n]
            self.top_n_correct += int(np.sum(order == actual[:, None]))
        else:
            self.top_n_correct += int(np.sum(actual == pred))

    def eval_single(self, actual: int, predicted: int):
        """One (actual, predicted) pair (reference `eval(int,int)` :461)."""
        if self.confusion is None:
            if self.num_classes is None:
                raise ValueError("num_classes required for eval_single")
            self._ensure(self.num_classes)
        self.confusion.matrix[actual, predicted] += 1
        self.total += 1
        if actual == predicted:
            self.top_n_correct += 1

    # ---- counts ----------------------------------------------------------
    def true_positives(self) -> Dict[int, int]:
        return {i: int(self.confusion.matrix[i, i]) for i in range(self.num_classes)}

    def false_positives(self) -> Dict[int, int]:
        return {i: int(self.confusion.matrix[:, i].sum() - self.confusion.matrix[i, i])
                for i in range(self.num_classes)}

    def false_negatives(self) -> Dict[int, int]:
        return {i: int(self.confusion.matrix[i, :].sum() - self.confusion.matrix[i, i])
                for i in range(self.num_classes)}

    def true_negatives(self) -> Dict[int, int]:
        total = self.confusion.matrix.sum()
        return {i: int(total - self.confusion.matrix[i, :].sum()
                       - self.confusion.matrix[:, i].sum() + self.confusion.matrix[i, i])
                for i in range(self.num_classes)}

    def positive(self) -> Dict[int, int]:
        """Actual occurrences per class (reference :1262)."""
        return {i: int(self.confusion.matrix[i, :].sum())
                for i in range(self.num_classes)}

    def negative(self) -> Dict[int, int]:
        """Actual non-occurrences per class (reference :1254)."""
        total = self.confusion.matrix.sum()
        return {i: int(total - self.confusion.matrix[i, :].sum())
                for i in range(self.num_classes)}

    def class_count(self, cls: int) -> int:
        """#examples whose actual class is `cls` (reference :1332)."""
        return int(self.confusion.matrix[cls, :].sum())

    def get_num_row_counter(self) -> int:
        return self.total

    def get_class_label(self, cls: int) -> str:
        if self.labels_names and cls < len(self.labels_names):
            return self.labels_names[cls]
        return str(cls)

    # ---- per-example metadata (reference Evaluation.java meta overloads)
    def get_prediction_errors(self):
        return self._ledger.get_prediction_errors()

    def get_predictions_by_actual_class(self, cls: int):
        return self._ledger.get_predictions_by_actual_class(cls)

    def get_predictions_by_predicted_class(self, cls: int):
        return self._ledger.get_predictions_by_predicted_class(cls)

    def get_predictions(self, actual: int, predicted: int):
        return self._ledger.get_predictions(actual, predicted)

    # ---- metrics ---------------------------------------------------------
    def accuracy(self) -> float:
        if self.total == 0:
            return 0.0
        return float(np.trace(self.confusion.matrix)) / self.total

    def top_n_accuracy(self) -> float:
        return self.top_n_correct / self.total if self.total else 0.0

    def _averaged(self, per_class_fn, averaging, micro_num_fn, micro_den_fn):
        if averaging in (None, EvaluationAveraging.MACRO, "macro"):
            vals = [per_class_fn(i) for i in range(self.num_classes)]
            return float(np.mean(vals)) if vals else 0.0
        num = sum(micro_num_fn(i) for i in range(self.num_classes))
        den = sum(micro_den_fn(i) for i in range(self.num_classes))
        return float(num / den) if den else 0.0

    def precision(self, cls: Optional[int] = None, averaging=None) -> float:
        if cls is not None:
            denom = self.confusion.matrix[:, cls].sum()
            return float(self.confusion.matrix[cls, cls] / denom) if denom else 0.0
        if averaging is not None:
            tp, fp = self.true_positives(), self.false_positives()
            return self._averaged(self.precision, averaging,
                                  lambda i: tp[i], lambda i: tp[i] + fp[i])
        vals = [self.precision(i) for i in range(self.num_classes)
                if self.confusion.matrix[:, i].sum() > 0 or self.confusion.matrix[i, :].sum() > 0]
        return float(np.mean(vals)) if vals else 0.0

    def recall(self, cls: Optional[int] = None, averaging=None) -> float:
        if cls is not None:
            denom = self.confusion.matrix[cls, :].sum()
            return float(self.confusion.matrix[cls, cls] / denom) if denom else 0.0
        if averaging is not None:
            tp, fn = self.true_positives(), self.false_negatives()
            return self._averaged(self.recall, averaging,
                                  lambda i: tp[i], lambda i: tp[i] + fn[i])
        vals = [self.recall(i) for i in range(self.num_classes)
                if self.confusion.matrix[i, :].sum() > 0]
        return float(np.mean(vals)) if vals else 0.0

    def false_positive_rate(self, cls: Optional[int] = None,
                            averaging=None) -> float:
        """FP / (FP + TN) (reference :851-885)."""
        if cls is not None:
            fp = self.false_positives()[cls]
            tn = self.true_negatives()[cls]
            return float(fp / (fp + tn)) if (fp + tn) else 0.0
        fp, tn = self.false_positives(), self.true_negatives()
        return self._averaged(self.false_positive_rate, averaging,
                              lambda i: fp[i], lambda i: fp[i] + tn[i])

    def false_negative_rate(self, cls: Optional[int] = None,
                            averaging=None) -> float:
        """FN / (FN + TP) (reference :913-947)."""
        if cls is not None:
            fn = self.false_negatives()[cls]
            tp = self.true_positives()[cls]
            return float(fn / (fn + tp)) if (fn + tp) else 0.0
        fn, tp = self.false_negatives(), self.true_positives()
        return self._averaged(self.false_negative_rate, averaging,
                              lambda i: fn[i], lambda i: fn[i] + tp[i])

    def false_alarm_rate(self) -> float:
        """(FPR + FNR) / 2 (reference :975)."""
        return (self.false_positive_rate() + self.false_negative_rate()) / 2.0

    def f_beta(self, beta: float, cls: Optional[int] = None,
               averaging=None) -> float:
        """F_beta = (1+β²)·P·R / (β²·P + R) (reference :998-1050)."""
        if cls is not None:
            p, r = self.precision(cls), self.recall(cls)
            d = beta * beta * p + r
            return float((1 + beta * beta) * p * r / d) if d else 0.0
        if averaging in (EvaluationAveraging.MICRO, "micro"):
            p = self.precision(averaging=EvaluationAveraging.MICRO)
            r = self.recall(averaging=EvaluationAveraging.MICRO)
            d = beta * beta * p + r
            return float((1 + beta * beta) * p * r / d) if d else 0.0
        vals = [self.f_beta(beta, i) for i in range(self.num_classes)]
        return float(np.mean(vals)) if vals else 0.0

    def f1(self, cls: Optional[int] = None, averaging=None) -> float:
        if cls is not None:
            return self.f_beta(1.0, cls)
        if averaging is not None:
            return self.f_beta(1.0, averaging=averaging)
        vals = [self.f1(i) for i in range(self.num_classes)
                if self.confusion.matrix[i, :].sum() > 0]
        return float(np.mean(vals)) if vals else 0.0

    def gmeasure(self, cls: Optional[int] = None, averaging=None) -> float:
        if cls is not None:
            return float(np.sqrt(self.precision(cls) * self.recall(cls)))
        if averaging in (EvaluationAveraging.MICRO, "micro"):
            p = self.precision(averaging=EvaluationAveraging.MICRO)
            r = self.recall(averaging=EvaluationAveraging.MICRO)
            return float(np.sqrt(p * r))
        vals = [self.gmeasure(i) for i in range(self.num_classes)]
        return float(np.mean(vals)) if vals else 0.0

    def matthews_correlation(self, cls: Optional[int] = None,
                             averaging=None) -> float:
        if cls is None:
            if averaging in (EvaluationAveraging.MICRO, "micro"):
                # reference :1184 MICRO: one MCC from the summed counts
                tp = sum(self.true_positives().values())
                fp = sum(self.false_positives().values())
                fn = sum(self.false_negatives().values())
                tn = sum(self.true_negatives().values())
                denom = np.sqrt(float(tp + fp) * (tp + fn)
                                * (tn + fp) * (tn + fn))
                return float((tp * tn - fp * fn) / denom) if denom else 0.0
            vals = [self.matthews_correlation(i)
                    for i in range(self.num_classes)]
            return float(np.mean(vals)) if vals else 0.0
        tp = self.true_positives()[cls]
        fp = self.false_positives()[cls]
        fn = self.false_negatives()[cls]
        tn = self.true_negatives()[cls]
        denom = np.sqrt(float(tp + fp) * (tp + fn) * (tn + fp) * (tn + fn))
        return float((tp * tn - fp * fn) / denom) if denom else 0.0

    # ---- reporting -------------------------------------------------------
    def warnings(self) -> List[str]:
        """Degenerate-class warnings the reference surfaces in stats()
        (classes never predicted / absent from the data)."""
        out = []
        if self.confusion is None:
            return ["evaluation saw no data"]
        for i in range(self.num_classes):
            name = self.get_class_label(i)
            if self.confusion.matrix[i, :].sum() == 0:
                out.append(f"class {name} never appeared as an actual label")
            elif self.confusion.matrix[:, i].sum() == 0:
                out.append(f"class {name} was never predicted by the model")
        return out

    def stats(self, suppress_warnings: bool = False,
              include_per_class: bool = True) -> str:
        """Label-name-aware report (reference stats() :499-509)."""
        lines = ["========================Evaluation Metrics========================",
                 f" # of classes:    {self.num_classes}",
                 f" Accuracy:        {self.accuracy():.4f}",
                 f" Precision:       {self.precision():.4f}",
                 f" Recall:          {self.recall():.4f}",
                 f" F1 Score:        {self.f1():.4f}"]
        if self.top_n > 1:
            lines.append(f" Top-{self.top_n} Accuracy: {self.top_n_accuracy():.4f}")
        if include_per_class and self.num_classes:
            w = max([5] + [len(self.get_class_label(i))
                           for i in range(self.num_classes)])
            lines.append("")
            lines.append(f" {'Label':<{w}}  Precision  Recall   F1       "
                         f"FPR      FNR      Count")
            for i in range(self.num_classes):
                lines.append(
                    f" {self.get_class_label(i):<{w}}  "
                    f"{self.precision(i):<9.4f}  {self.recall(i):<7.4f}  "
                    f"{self.f1(i):<7.4f}  {self.false_positive_rate(i):<7.4f}  "
                    f"{self.false_negative_rate(i):<7.4f}  {self.class_count(i)}")
        if not suppress_warnings:
            warns = self.warnings()
            if warns:
                lines.append("")
                lines.extend(f" Warning: {wmsg}" for wmsg in warns)
        lines.append("\n=========================Confusion Matrix=========================")
        if self.labels_names:
            lines.append(" labels: " + ", ".join(
                f"{i}={self.get_class_label(i)}"
                for i in range(self.num_classes)))
        lines.append(str(self.confusion))
        return "\n".join(lines)

    # ---- serde (reference BaseEvaluation.toJson/fromJson) ---------------
    def to_json(self) -> str:
        return json.dumps({
            "format_version": 1,
            "type": "Evaluation",
            "num_classes": self.num_classes,
            "top_n": self.top_n,
            "top_n_correct": self.top_n_correct,
            "total": self.total,
            "labels_names": self.labels_names,
            "binary_decision_threshold": self.binary_decision_threshold,
            "cost_array": (None if self.cost_array is None
                           else self.cost_array.tolist()),
            "confusion": (None if self.confusion is None
                          else self.confusion.matrix.tolist()),
        })

    @classmethod
    def from_json(cls, s: str) -> "Evaluation":
        d = json.loads(s)
        check_payload_type(d, "Evaluation")
        ev = cls(num_classes=d["num_classes"], top_n=d["top_n"],
                 labels_names=d.get("labels_names"),
                 binary_decision_threshold=d.get("binary_decision_threshold"),
                 cost_array=d.get("cost_array"))
        ev.top_n_correct = d["top_n_correct"]
        ev.total = d["total"]
        if d.get("confusion") is not None:
            ev._ensure(d["num_classes"])
            ev.confusion.matrix = np.asarray(d["confusion"], np.int64)
        return ev

    def merge(self, other: "Evaluation"):
        if other.confusion is None:
            return self
        self._ensure(other.num_classes)
        self.confusion.matrix += other.confusion.matrix
        self.total += other.total
        self.top_n_correct += other.top_n_correct
        return self
