"""Classification evaluation with confusion matrix.

Reference: `eval/Evaluation.java` (1,627 LoC): `eval()` accumulates a
confusion matrix from (labels, predictions); metrics: accuracy :1138,
precision :664, recall :803, f1 :1031, plus topN, per-class counts,
stats() report. Time-series inputs are flattened with mask support
(`evalTimeSeries`).
"""

from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np


class ConfusionMatrix:
    def __init__(self, num_classes: int):
        self.matrix = np.zeros((num_classes, num_classes), dtype=np.int64)

    def add(self, actual: np.ndarray, predicted: np.ndarray):
        np.add.at(self.matrix, (actual, predicted), 1)

    def get_count(self, actual: int, predicted: int) -> int:
        return int(self.matrix[actual, predicted])

    def __str__(self):
        return str(self.matrix)


def _flatten_time_series(labels, preds, mask):
    """[B,T,C] → [B*T, C], dropping masked steps (reference
    evalTimeSeries + MaskedReductionUtil)."""
    labels = np.asarray(labels)
    preds = np.asarray(preds)
    if labels.ndim == 3:
        b, t, c = labels.shape
        labels = labels.reshape(b * t, c)
        preds = preds.reshape(b * t, c)
        if mask is not None:
            m = np.asarray(mask).reshape(-1).astype(bool)
            labels, preds = labels[m], preds[m]
    return labels, preds


class Evaluation:
    def __init__(self, num_classes: Optional[int] = None, top_n: int = 1,
                 labels_names: Optional[List[str]] = None):
        self.num_classes = num_classes
        self.top_n = top_n
        self.labels_names = labels_names
        self.confusion: Optional[ConfusionMatrix] = None
        self.top_n_correct = 0
        self.total = 0
        # per-example Prediction tracking, populated only when eval() is
        # given record metadata (reference eval/meta/Prediction.java)
        from deeplearning4j_tpu.eval.meta import PredictionLedger
        self._ledger = PredictionLedger()

    def _ensure(self, c):
        if self.confusion is None:
            self.num_classes = self.num_classes or c
            self.confusion = ConfusionMatrix(self.num_classes)

    def eval(self, labels, predictions, mask=None, record_metadata=None):
        labels, predictions = _flatten_time_series(labels, predictions, mask)
        self._ensure(labels.shape[-1])
        actual = np.argmax(labels, axis=-1)
        pred = np.argmax(predictions, axis=-1)
        if record_metadata is not None:
            # time-series flattening / masking can change the row count;
            # silently misaligned attribution would be worse than failing
            if len(record_metadata) != len(actual):
                raise ValueError(
                    f"record_metadata has {len(record_metadata)} entries but "
                    f"evaluation flattened/masked to {len(actual)} rows; "
                    "per-example metadata tracking supports 2-d labels (or "
                    "pre-flattened metadata aligned with kept rows)")
            self._ledger.record(actual, pred, record_metadata)
        self.confusion.add(actual, pred)
        self.total += len(actual)
        if self.top_n > 1:
            order = np.argsort(predictions, axis=-1)[:, ::-1][:, :self.top_n]
            self.top_n_correct += int(np.sum(order == actual[:, None]))
        else:
            self.top_n_correct += int(np.sum(actual == pred))

    # ---- counts ----------------------------------------------------------
    def true_positives(self) -> Dict[int, int]:
        return {i: int(self.confusion.matrix[i, i]) for i in range(self.num_classes)}

    def false_positives(self) -> Dict[int, int]:
        return {i: int(self.confusion.matrix[:, i].sum() - self.confusion.matrix[i, i])
                for i in range(self.num_classes)}

    def false_negatives(self) -> Dict[int, int]:
        return {i: int(self.confusion.matrix[i, :].sum() - self.confusion.matrix[i, i])
                for i in range(self.num_classes)}

    def true_negatives(self) -> Dict[int, int]:
        total = self.confusion.matrix.sum()
        return {i: int(total - self.confusion.matrix[i, :].sum()
                       - self.confusion.matrix[:, i].sum() + self.confusion.matrix[i, i])
                for i in range(self.num_classes)}

    # ---- per-example metadata (reference Evaluation.java meta overloads)
    def get_prediction_errors(self):
        return self._ledger.get_prediction_errors()

    def get_predictions_by_actual_class(self, cls: int):
        return self._ledger.get_predictions_by_actual_class(cls)

    def get_predictions_by_predicted_class(self, cls: int):
        return self._ledger.get_predictions_by_predicted_class(cls)

    def get_predictions(self, actual: int, predicted: int):
        return self._ledger.get_predictions(actual, predicted)

    # ---- metrics ---------------------------------------------------------
    def accuracy(self) -> float:
        if self.total == 0:
            return 0.0
        return float(np.trace(self.confusion.matrix)) / self.total

    def top_n_accuracy(self) -> float:
        return self.top_n_correct / self.total if self.total else 0.0

    def precision(self, cls: Optional[int] = None) -> float:
        if cls is not None:
            denom = self.confusion.matrix[:, cls].sum()
            return float(self.confusion.matrix[cls, cls] / denom) if denom else 0.0
        vals = [self.precision(i) for i in range(self.num_classes)
                if self.confusion.matrix[:, i].sum() > 0 or self.confusion.matrix[i, :].sum() > 0]
        return float(np.mean(vals)) if vals else 0.0

    def recall(self, cls: Optional[int] = None) -> float:
        if cls is not None:
            denom = self.confusion.matrix[cls, :].sum()
            return float(self.confusion.matrix[cls, cls] / denom) if denom else 0.0
        vals = [self.recall(i) for i in range(self.num_classes)
                if self.confusion.matrix[i, :].sum() > 0]
        return float(np.mean(vals)) if vals else 0.0

    def f1(self, cls: Optional[int] = None) -> float:
        if cls is not None:
            p, r = self.precision(cls), self.recall(cls)
            return 2 * p * r / (p + r) if (p + r) else 0.0
        vals = [self.f1(i) for i in range(self.num_classes)
                if self.confusion.matrix[i, :].sum() > 0]
        return float(np.mean(vals)) if vals else 0.0

    def gmeasure(self, cls: int) -> float:
        return float(np.sqrt(self.precision(cls) * self.recall(cls)))

    def matthews_correlation(self, cls: int) -> float:
        tp = self.true_positives()[cls]
        fp = self.false_positives()[cls]
        fn = self.false_negatives()[cls]
        tn = self.true_negatives()[cls]
        denom = np.sqrt(float(tp + fp) * (tp + fn) * (tn + fp) * (tn + fn))
        return float((tp * tn - fp * fn) / denom) if denom else 0.0

    def stats(self) -> str:
        lines = ["========================Evaluation Metrics========================",
                 f" # of classes:    {self.num_classes}",
                 f" Accuracy:        {self.accuracy():.4f}",
                 f" Precision:       {self.precision():.4f}",
                 f" Recall:          {self.recall():.4f}",
                 f" F1 Score:        {self.f1():.4f}"]
        if self.top_n > 1:
            lines.append(f" Top-{self.top_n} Accuracy: {self.top_n_accuracy():.4f}")
        lines.append("\n=========================Confusion Matrix=========================")
        lines.append(str(self.confusion))
        return "\n".join(lines)

    def merge(self, other: "Evaluation"):
        if other.confusion is None:
            return self
        self._ensure(other.num_classes)
        self.confusion.matrix += other.confusion.matrix
        self.total += other.total
        self.top_n_correct += other.top_n_correct
        return self
