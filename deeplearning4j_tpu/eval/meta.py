"""Per-example prediction metadata tracking.

Reference: `eval/meta/Prediction.java` + the
`Evaluation.eval(labels, out, recordMetaData)` overload — when the data
pipeline carries record metadata (e.g. source file + line of each
example), evaluation keeps one `Prediction` per example so
misclassifications can be traced back to their records
(`getPredictionErrors()` etc., `EvaluationTools` error inspection).
"""

from __future__ import annotations

import dataclasses
from typing import Any, List


@dataclasses.dataclass
class Prediction:
    """Reference `Prediction.java`: (actual, predicted, record metadata)."""

    actual_class: int
    predicted_class: int
    record_metadata: Any = None

    def __repr__(self):
        return (f"Prediction(actual={self.actual_class}, "
                f"predicted={self.predicted_class}, "
                f"record_metadata={self.record_metadata!r})")


class PredictionLedger:
    """Accumulates Predictions across eval() batches (mixed into
    Evaluation)."""

    def __init__(self):
        self.predictions: List[Prediction] = []

    def record(self, actual, predicted, metadata_list):
        for a, p, m in zip(actual, predicted, metadata_list):
            self.predictions.append(Prediction(int(a), int(p), m))

    def get_prediction_errors(self) -> List[Prediction]:
        """Reference `getPredictionErrors()`."""
        return [p for p in self.predictions
                if p.actual_class != p.predicted_class]

    def get_predictions_by_actual_class(self, cls: int) -> List[Prediction]:
        return [p for p in self.predictions if p.actual_class == cls]

    def get_predictions_by_predicted_class(self, cls: int) -> List[Prediction]:
        return [p for p in self.predictions if p.predicted_class == cls]

    def get_predictions(self, actual: int, predicted: int) -> List[Prediction]:
        """Reference `getPredictions(actual, predicted)` — one confusion
        matrix cell's examples."""
        return [p for p in self.predictions
                if p.actual_class == actual and p.predicted_class == predicted]
