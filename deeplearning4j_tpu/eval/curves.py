"""Serializable evaluation curves.

Reference: `eval/curves/` — `RocCurve.java` (threshold/fpr/tpr triples,
JSON round-trip, point queries), `PrecisionRecallCurve.java`
(threshold/precision/recall + point-at-threshold helpers),
`Histogram.java`, `ReliabilityDiagram.java`. These are the wire format
that lets a curve computed during training be stored, shipped to the
UI, and re-plotted without the raw scores.
"""

from __future__ import annotations

import json
from typing import List, Optional

import numpy as np


class BaseCurve:
    _fields: tuple = ()

    def to_dict(self) -> dict:
        out = {"type": type(self).__name__}
        for f in self._fields:
            v = getattr(self, f)
            out[f] = v.tolist() if isinstance(v, np.ndarray) else v
        return out

    def to_json(self) -> str:
        return json.dumps(self.to_dict())

    @classmethod
    def from_json(cls, s: str) -> "BaseCurve":
        d = json.loads(s)
        if d.pop("type", cls.__name__) != cls.__name__:
            raise ValueError(f"not a serialized {cls.__name__}")
        return cls(**d)

    def __eq__(self, other):
        if type(self) is not type(other):
            return NotImplemented
        for f in self._fields:
            a, b = getattr(self, f), getattr(other, f)
            if isinstance(a, np.ndarray):
                if not np.allclose(a, np.asarray(b)):
                    return False
            elif a != b:
                return False
        return True


class RocCurve(BaseCurve):
    """Reference `RocCurve.java`: parallel threshold/fpr/tpr arrays."""

    _fields = ("thresholds", "fpr", "tpr")

    def __init__(self, thresholds, fpr, tpr):
        self.thresholds = np.asarray(thresholds, np.float64)
        self.fpr = np.asarray(fpr, np.float64)
        self.tpr = np.asarray(tpr, np.float64)

    def num_points(self) -> int:
        return len(self.fpr)

    def get_threshold(self, i) -> float:
        return float(self.thresholds[i])

    def get_false_positive_rate(self, i) -> float:
        return float(self.fpr[i])

    def get_true_positive_rate(self, i) -> float:
        return float(self.tpr[i])

    def calculate_auc(self) -> float:
        return float(np.trapezoid(self.tpr, self.fpr))


class PrecisionRecallCurve(BaseCurve):
    """Reference `PrecisionRecallCurve.java` incl. the point queries
    used to pick an operating threshold."""

    _fields = ("thresholds", "precision", "recall")

    def __init__(self, thresholds, precision, recall):
        self.thresholds = np.asarray(thresholds, np.float64)
        self.precision = np.asarray(precision, np.float64)
        self.recall = np.asarray(recall, np.float64)

    def num_points(self) -> int:
        return len(self.precision)

    def calculate_auprc(self) -> float:
        order = np.argsort(self.recall)
        return float(np.trapezoid(self.precision[order], self.recall[order]))

    def get_point_at_threshold(self, threshold: float):
        """(threshold, precision, recall) at the smallest stored
        threshold ≥ requested — never an operating point below the
        requested threshold (reference `getPointAtThreshold`); falls
        back to the highest stored threshold when none qualifies."""
        ok = np.nonzero(self.thresholds >= threshold)[0]
        if len(ok) == 0:
            i = int(np.argmax(self.thresholds))
        else:
            i = ok[int(np.argmin(self.thresholds[ok]))]
        return (float(self.thresholds[i]), float(self.precision[i]),
                float(self.recall[i]))

    def get_point_at_precision(self, min_precision: float):
        """Best-recall point with precision ≥ min_precision."""
        ok = np.nonzero(self.precision >= min_precision)[0]
        if len(ok) == 0:   # fall back to max-precision point
            i = int(np.argmax(self.precision))
        else:
            i = ok[int(np.argmax(self.recall[ok]))]
        return (float(self.thresholds[i]), float(self.precision[i]),
                float(self.recall[i]))

    def get_point_at_recall(self, min_recall: float):
        """Best-precision point with recall ≥ min_recall."""
        ok = np.nonzero(self.recall >= min_recall)[0]
        if len(ok) == 0:
            i = int(np.argmax(self.recall))
        else:
            i = ok[int(np.argmax(self.precision[ok]))]
        return (float(self.thresholds[i]), float(self.precision[i]),
                float(self.recall[i]))


class Histogram(BaseCurve):
    """Reference `Histogram.java`: titled, uniformly-binned counts."""

    _fields = ("title", "lower", "upper", "bin_counts")

    def __init__(self, title, lower, upper, bin_counts):
        self.title = title
        self.lower = float(lower)
        self.upper = float(upper)
        self.bin_counts = np.asarray(bin_counts, np.int64)

    def num_bins(self) -> int:
        return len(self.bin_counts)

    def bin_edges(self) -> np.ndarray:
        return np.linspace(self.lower, self.upper, len(self.bin_counts) + 1)


class ReliabilityDiagram(BaseCurve):
    """Reference `ReliabilityDiagram.java`: mean predicted probability
    vs observed frequency per calibration bin."""

    _fields = ("title", "mean_predicted", "fraction_positives")

    def __init__(self, title, mean_predicted, fraction_positives):
        self.title = title
        self.mean_predicted = np.asarray(mean_predicted, np.float64)
        self.fraction_positives = np.asarray(fraction_positives, np.float64)

    def num_points(self) -> int:
        return len(self.mean_predicted)
