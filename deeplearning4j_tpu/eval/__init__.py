"""Evaluation suite (reference: `deeplearning4j-nn/.../eval/`):
Evaluation (classification + confusion matrix), RegressionEvaluation,
ROC / ROCBinary / ROCMultiClass, EvaluationBinary,
EvaluationCalibration.
"""

from deeplearning4j_tpu.eval.evaluation import Evaluation, ConfusionMatrix
from deeplearning4j_tpu.eval.regression import RegressionEvaluation
from deeplearning4j_tpu.eval.roc import ROC, ROCBinary, ROCMultiClass
from deeplearning4j_tpu.eval.binary import EvaluationBinary
from deeplearning4j_tpu.eval.calibration import EvaluationCalibration
from deeplearning4j_tpu.eval.curves import (
    Histogram,
    PrecisionRecallCurve,
    ReliabilityDiagram,
    RocCurve,
)
from deeplearning4j_tpu.eval.meta import Prediction
