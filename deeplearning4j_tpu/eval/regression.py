"""Regression evaluation.

Reference: `eval/RegressionEvaluation.java`: per-column MSE, MAE, RMSE,
RSE (relative squared error), R² (correlation-based in the reference),
with mask support for time series.
"""

from __future__ import annotations

from typing import Optional

import json

import numpy as np

from deeplearning4j_tpu.eval.evaluation import check_payload_type


class RegressionEvaluation:
    def __init__(self, num_columns: Optional[int] = None, column_names=None):
        self.num_columns = num_columns
        self.column_names = column_names
        self._sum_err2 = None
        self._sum_abs = None
        self._sum_label = None
        self._sum_label2 = None
        self._sum_pred = None
        self._sum_pred2 = None
        self._sum_label_pred = None
        self._count = None

    def _ensure(self, c):
        if self._sum_err2 is None:
            self.num_columns = self.num_columns or c
            z = lambda: np.zeros(self.num_columns, dtype=np.float64)
            self._sum_err2, self._sum_abs = z(), z()
            self._sum_label, self._sum_label2 = z(), z()
            self._sum_pred, self._sum_pred2 = z(), z()
            self._sum_label_pred, self._count = z(), z()

    def eval(self, labels, predictions, mask=None):
        labels = np.asarray(labels, dtype=np.float64)
        predictions = np.asarray(predictions, dtype=np.float64)
        if labels.ndim == 3:
            b, t, c = labels.shape
            labels = labels.reshape(-1, c)
            predictions = predictions.reshape(-1, c)
            if mask is not None:
                m = np.asarray(mask).reshape(-1).astype(bool)
                labels, predictions = labels[m], predictions[m]
        self._ensure(labels.shape[-1])
        err = predictions - labels
        self._sum_err2 += np.sum(err ** 2, axis=0)
        self._sum_abs += np.sum(np.abs(err), axis=0)
        self._sum_label += np.sum(labels, axis=0)
        self._sum_label2 += np.sum(labels ** 2, axis=0)
        self._sum_pred += np.sum(predictions, axis=0)
        self._sum_pred2 += np.sum(predictions ** 2, axis=0)
        self._sum_label_pred += np.sum(labels * predictions, axis=0)
        self._count += labels.shape[0]

    def mean_squared_error(self, col: int) -> float:
        return float(self._sum_err2[col] / self._count[col])

    def mean_absolute_error(self, col: int) -> float:
        return float(self._sum_abs[col] / self._count[col])

    def root_mean_squared_error(self, col: int) -> float:
        return float(np.sqrt(self.mean_squared_error(col)))

    def relative_squared_error(self, col: int) -> float:
        n = self._count[col]
        mean_label = self._sum_label[col] / n
        ss_tot = self._sum_label2[col] - n * mean_label ** 2
        return float(self._sum_err2[col] / ss_tot) if ss_tot else float("inf")

    def correlation_r2(self, col: int) -> float:
        n = self._count[col]
        cov = self._sum_label_pred[col] - self._sum_label[col] * self._sum_pred[col] / n
        var_l = self._sum_label2[col] - self._sum_label[col] ** 2 / n
        var_p = self._sum_pred2[col] - self._sum_pred[col] ** 2 / n
        denom = np.sqrt(var_l * var_p)
        return float((cov / denom) ** 2) if denom else 0.0

    def average_mean_squared_error(self) -> float:
        return float(np.mean(self._sum_err2 / self._count))

    def average_mean_absolute_error(self) -> float:
        return float(np.mean(self._sum_abs / self._count))

    def average_root_mean_squared_error(self) -> float:
        return float(np.mean(np.sqrt(self._sum_err2 / self._count)))


    # ---- serde (reference BaseEvaluation.toJson/fromJson) ----------------
    _SUM_FIELDS = ("_sum_err2", "_sum_abs", "_sum_label", "_sum_label2",
                   "_sum_pred", "_sum_pred2", "_sum_label_pred", "_count")

    def to_json(self) -> str:
        d = {"format_version": 1, "type": "RegressionEvaluation",
             "num_columns": self.num_columns,
             "column_names": self.column_names}
        for f in self._SUM_FIELDS:
            v = getattr(self, f)
            d[f] = None if v is None else v.tolist()
        return json.dumps(d)

    @classmethod
    def from_json(cls, s: str) -> "RegressionEvaluation":
        d = json.loads(s)
        check_payload_type(d, "RegressionEvaluation")
        ev = cls(num_columns=d["num_columns"], column_names=d.get("column_names"))
        for f in cls._SUM_FIELDS:
            if d.get(f) is not None:
                setattr(ev, f, np.asarray(d[f], np.float64))
        return ev

    def merge(self, other: "RegressionEvaluation") -> "RegressionEvaluation":
        """Accumulator merge (the Spark tree-aggregate role)."""
        if other._sum_err2 is None:
            return self
        self._ensure(other.num_columns)
        for f in self._SUM_FIELDS:
            setattr(self, f, getattr(self, f) + getattr(other, f))
        return self

    def stats(self) -> str:
        lines = ["Column    MSE            MAE            RMSE           RSE            R^2"]
        for c in range(self.num_columns):
            name = self.column_names[c] if self.column_names else f"col_{c}"
            lines.append(f"{name:<9} {self.mean_squared_error(c):<14.6g} "
                         f"{self.mean_absolute_error(c):<14.6g} "
                         f"{self.root_mean_squared_error(c):<14.6g} "
                         f"{self.relative_squared_error(c):<14.6g} "
                         f"{self.correlation_r2(c):<14.6g}")
        return "\n".join(lines)
