"""Per-output binary evaluation (multi-label).

Reference: `eval/EvaluationBinary.java`: each output column is an
independent binary problem at threshold 0.5 (configurable); tracks
TP/FP/TN/FN per column with mask support.
"""

from __future__ import annotations

from typing import Optional

import json

import numpy as np

from deeplearning4j_tpu.eval.evaluation import check_payload_type


class EvaluationBinary:
    def __init__(self, threshold: float = 0.5):
        self.threshold = threshold
        self._tp = None
        self._fp = None
        self._tn = None
        self._fn = None

    def _ensure(self, c):
        if self._tp is None:
            z = lambda: np.zeros(c, dtype=np.int64)
            self._tp, self._fp, self._tn, self._fn = z(), z(), z(), z()

    def eval(self, labels, predictions, mask=None):
        labels = np.asarray(labels)
        predictions = np.asarray(predictions)
        if labels.ndim == 3:
            c = labels.shape[-1]
            labels = labels.reshape(-1, c)
            predictions = predictions.reshape(-1, c)
            if mask is not None:
                m = np.asarray(mask).reshape(-1).astype(bool)
                labels, predictions = labels[m], predictions[m]
        self._ensure(labels.shape[-1])
        pred = predictions >= self.threshold
        lab = labels >= 0.5
        self._tp += np.sum(pred & lab, axis=0)
        self._fp += np.sum(pred & ~lab, axis=0)
        self._tn += np.sum(~pred & ~lab, axis=0)
        self._fn += np.sum(~pred & lab, axis=0)

    def num_labels(self) -> int:
        return 0 if self._tp is None else len(self._tp)

    def accuracy(self, col: int) -> float:
        total = self._tp[col] + self._fp[col] + self._tn[col] + self._fn[col]
        return float((self._tp[col] + self._tn[col]) / total) if total else 0.0

    def precision(self, col: int) -> float:
        denom = self._tp[col] + self._fp[col]
        return float(self._tp[col] / denom) if denom else 0.0

    def recall(self, col: int) -> float:
        denom = self._tp[col] + self._fn[col]
        return float(self._tp[col] / denom) if denom else 0.0

    def f1(self, col: int) -> float:
        p, r = self.precision(col), self.recall(col)
        return 2 * p * r / (p + r) if (p + r) else 0.0

    def true_positives(self, col: int) -> int:
        return int(self._tp[col])

    def false_positives(self, col: int) -> int:
        return int(self._fp[col])

    def true_negatives(self, col: int) -> int:
        return int(self._tn[col])

    def false_negatives(self, col: int) -> int:
        return int(self._fn[col])


    # ---- serde + merge (tree-aggregate shape) ----------------------------
    def to_json(self) -> str:
        return json.dumps({
            "format_version": 1, "type": "EvaluationBinary",
            "threshold": self.threshold,
            "tp": None if self._tp is None else self._tp.tolist(),
            "fp": None if self._fp is None else self._fp.tolist(),
            "tn": None if self._tn is None else self._tn.tolist(),
            "fn": None if self._fn is None else self._fn.tolist(),
        })

    @classmethod
    def from_json(cls, s: str) -> "EvaluationBinary":
        d = json.loads(s)
        check_payload_type(d, "EvaluationBinary")
        ev = cls(threshold=d.get("threshold", 0.5))
        if d.get("tp") is not None:
            for f, k in (("_tp", "tp"), ("_fp", "fp"), ("_tn", "tn"),
                         ("_fn", "fn")):
                setattr(ev, f, np.asarray(d[k], np.int64))
        return ev

    def merge(self, other: "EvaluationBinary") -> "EvaluationBinary":
        if other._tp is None:
            return self
        if other.threshold != self.threshold:
            # counts taken at different decision thresholds sum to
            # numbers that correspond to NO threshold — refuse
            raise ValueError(
                f"cannot merge EvaluationBinary at threshold "
                f"{other.threshold} into one at {self.threshold}")
        self._ensure(len(other._tp))
        self._tp += other._tp
        self._fp += other._fp
        self._tn += other._tn
        self._fn += other._fn
        return self

    def stats(self) -> str:
        lines = ["Label   Acc     Precision Recall  F1"]
        for c in range(self.num_labels()):
            lines.append(f"{c:<7} {self.accuracy(c):<7.4f} {self.precision(c):<9.4f} "
                         f"{self.recall(c):<7.4f} {self.f1(c):<7.4f}")
        return "\n".join(lines)
