"""ROC / AUC evaluation.

Reference: `eval/ROC.java` (exact mode when thresholdSteps==0, else
thresholded), `ROCBinary.java` (per-output binary), `ROCMultiClass.java`
(one-vs-all per class). AUROC via trapezoidal rule on the exact curve;
AUPRC likewise.
"""

from __future__ import annotations

from typing import List, Optional

import json

import numpy as np

from deeplearning4j_tpu.eval.evaluation import check_payload_type


def _binary_roc_points(labels: np.ndarray, probs: np.ndarray):
    order = np.argsort(-probs, kind="stable")
    labels = labels[order]
    tp = np.cumsum(labels)
    fp = np.cumsum(1 - labels)
    total_pos = tp[-1] if len(tp) else 0
    total_neg = fp[-1] if len(fp) else 0
    tpr = tp / total_pos if total_pos else np.zeros_like(tp, dtype=np.float64)
    fpr = fp / total_neg if total_neg else np.zeros_like(fp, dtype=np.float64)
    return np.concatenate([[0.0], fpr]), np.concatenate([[0.0], tpr])


def _auc(x, y):
    return float(np.trapezoid(y, x))


class ROC:
    """Binary ROC. Accumulates raw (label, score) pairs → exact curve
    (reference exact mode, thresholdSteps=0)."""

    def __init__(self, threshold_steps: int = 0):
        self.threshold_steps = threshold_steps
        self._labels: List[np.ndarray] = []
        self._probs: List[np.ndarray] = []

    def eval(self, labels, predictions, mask=None):
        labels = np.asarray(labels)
        predictions = np.asarray(predictions)
        if labels.ndim == 3:
            c = labels.shape[-1]
            labels = labels.reshape(-1, c)
            predictions = predictions.reshape(-1, c)
            if mask is not None:
                m = np.asarray(mask).reshape(-1).astype(bool)
                labels, predictions = labels[m], predictions[m]
        if labels.ndim == 2 and labels.shape[-1] == 2:
            # [P(class0), P(class1)] convention: positive = column 1
            labels = labels[:, 1]
            predictions = predictions[:, 1]
        else:
            labels = labels.reshape(-1)
            predictions = predictions.reshape(-1)
        self._labels.append(labels.astype(np.float64))
        self._probs.append(predictions.astype(np.float64))

    def _collect(self):
        return np.concatenate(self._labels), np.concatenate(self._probs)

    def calculate_auc(self) -> float:
        labels, probs = self._collect()
        fpr, tpr = _binary_roc_points(labels, probs)
        return _auc(fpr, tpr)

    def calculate_auprc(self) -> float:
        labels, probs = self._collect()
        order = np.argsort(-probs, kind="stable")
        labels = labels[order]
        tp = np.cumsum(labels)
        k = np.arange(1, len(labels) + 1)
        precision = tp / k
        recall = tp / tp[-1] if tp[-1] else np.zeros_like(tp, dtype=np.float64)
        return _auc(np.concatenate([[0.0], recall]), np.concatenate([[1.0], precision]))

    def get_roc_curve(self):
        labels, probs = self._collect()
        return _binary_roc_points(labels, probs)

    def get_roc_curve_object(self):
        """Serializable curve (reference `ROC.getRocCurve()` ->
        `RocCurve.java`): thresholds descending with the (0,0) anchor at
        threshold 1+max."""
        from deeplearning4j_tpu.eval.curves import RocCurve
        labels, probs = self._collect()
        fpr, tpr = _binary_roc_points(labels, probs)
        order = np.argsort(-probs, kind="stable")
        thresholds = np.concatenate([[1.0 if len(probs) == 0
                                      else float(probs[order[0]]) + 1.0],
                                     probs[order]])
        return RocCurve(thresholds, fpr, tpr)

    def get_precision_recall_curve(self):
        """Reference `ROC.getPrecisionRecallCurve()` ->
        `PrecisionRecallCurve.java`."""
        from deeplearning4j_tpu.eval.curves import PrecisionRecallCurve
        labels, probs = self._collect()
        order = np.argsort(-probs, kind="stable")
        lab = labels[order]
        tp = np.cumsum(lab)
        n = np.arange(1, len(lab) + 1)
        precision = tp / n
        total_pos = tp[-1] if len(tp) else 0
        recall = (tp / total_pos if total_pos
                  else np.zeros_like(tp, dtype=np.float64))
        return PrecisionRecallCurve(probs[order], precision, recall)



    # ---- serde + merge (exact mode stores raw scores, so serialization
    # carries them — the reference's exact-mode ROC does the same via
    # its stored prediction arrays)
    def to_dict(self) -> dict:
        labels, probs = (self._collect() if self._labels
                         else (np.zeros(0), np.zeros(0)))
        return {"format_version": 1, "type": "ROC",
                "threshold_steps": self.threshold_steps,
                "labels": labels.tolist(), "probs": probs.tolist()}

    @classmethod
    def from_dict(cls, d: dict) -> "ROC":
        check_payload_type(d, "ROC")
        roc = cls(threshold_steps=d.get("threshold_steps", 0))
        if d.get("labels"):
            roc._labels.append(np.asarray(d["labels"], np.float64))
            roc._probs.append(np.asarray(d["probs"], np.float64))
        return roc

    def to_json(self) -> str:
        return json.dumps(self.to_dict())

    @classmethod
    def from_json(cls, s: str) -> "ROC":
        return cls.from_dict(json.loads(s))

    def merge(self, other: "ROC") -> "ROC":
        self._labels.extend(other._labels)
        self._probs.extend(other._probs)
        return self


class _ROCFamily:
    """Per-column serde/merge shared by ROCBinary and ROCMultiClass
    (both hold one exact-mode ROC per output column)."""

    _rocs: "Optional[List[ROC]]"

    def to_json(self) -> str:
        return json.dumps({
            "format_version": 1, "type": type(self).__name__,
            "columns": ([] if self._rocs is None
                        else [r.to_dict() for r in self._rocs]),
        })

    @classmethod
    def from_json(cls, s: str):
        d = json.loads(s)
        check_payload_type(d, cls.__name__)
        ev = cls()
        cols = d.get("columns")
        if cols is None:
            raise ValueError(f"{cls.__name__} payload has no 'columns'")
        if cols:
            ev._rocs = [ROC.from_dict(c) for c in cols]
        return ev

    def merge(self, other):
        if other._rocs is None:
            return self
        if self._rocs is None:
            # clone configuration, not just counts — a default ROC()
            # would silently drop the source's threshold_steps
            self._rocs = [ROC(threshold_steps=r.threshold_steps)
                          for r in other._rocs]
        if len(self._rocs) != len(other._rocs):
            raise ValueError("cannot merge ROC families with different "
                             "column counts")
        for a, b in zip(self._rocs, other._rocs):
            a.merge(b)
        return self


class ROCBinary(_ROCFamily):
    """Independent binary ROC per output column (reference
    `ROCBinary.java` for multi-label sigmoid outputs)."""

    def __init__(self, threshold_steps: int = 0):
        self.threshold_steps = threshold_steps
        self._rocs: Optional[List[ROC]] = None

    def eval(self, labels, predictions, mask=None):
        labels = np.asarray(labels)
        predictions = np.asarray(predictions)
        if labels.ndim == 3:
            c = labels.shape[-1]
            labels = labels.reshape(-1, c)
            predictions = predictions.reshape(-1, c)
        if self._rocs is None:
            self._rocs = [ROC(threshold_steps=self.threshold_steps)
                          for _ in range(labels.shape[-1])]
        for i, roc in enumerate(self._rocs):
            roc.eval(labels[:, i], predictions[:, i])

    def calculate_auc(self, col: int) -> float:
        return self._rocs[col].calculate_auc()

    def num_labels(self):
        return 0 if self._rocs is None else len(self._rocs)




class ROCMultiClass(_ROCFamily):
    """One-vs-all ROC per class (reference `ROCMultiClass.java`)."""

    def __init__(self, threshold_steps: int = 0):
        self.threshold_steps = threshold_steps
        self._rocs: Optional[List[ROC]] = None

    def eval(self, labels, predictions, mask=None):
        labels = np.asarray(labels)
        predictions = np.asarray(predictions)
        if labels.ndim == 3:
            c = labels.shape[-1]
            labels = labels.reshape(-1, c)
            predictions = predictions.reshape(-1, c)
            if mask is not None:
                m = np.asarray(mask).reshape(-1).astype(bool)
                labels, predictions = labels[m], predictions[m]
        if self._rocs is None:
            self._rocs = [ROC(threshold_steps=self.threshold_steps)
                          for _ in range(labels.shape[-1])]
        for i, roc in enumerate(self._rocs):
            roc.eval(labels[:, i], predictions[:, i])

    def calculate_auc(self, cls: int) -> float:
        return self._rocs[cls].calculate_auc()

    def calculate_average_auc(self) -> float:
        return float(np.mean([r.calculate_auc() for r in self._rocs]))


