"""Calibration evaluation: reliability diagram + histograms.

Reference: `eval/EvaluationCalibration.java`: bins predicted
probabilities, tracks observed positive fraction per bin (reliability
diagram data), residual plot + probability histogram.
"""

from __future__ import annotations

import json

import numpy as np

from deeplearning4j_tpu.eval.evaluation import check_payload_type


class EvaluationCalibration:
    def __init__(self, reliability_bins: int = 10, histogram_bins: int = 10):
        self.reliability_bins = reliability_bins
        self.histogram_bins = histogram_bins
        self._bin_counts = None
        self._bin_pos = None
        self._bin_prob_sum = None
        self._prob_hist = None

    def _ensure(self, c):
        if self._bin_counts is None:
            self._bin_counts = np.zeros((c, self.reliability_bins), dtype=np.int64)
            self._bin_pos = np.zeros((c, self.reliability_bins), dtype=np.int64)
            self._bin_prob_sum = np.zeros((c, self.reliability_bins), dtype=np.float64)
            self._prob_hist = np.zeros((c, self.histogram_bins), dtype=np.int64)
            self._residual_hist = np.zeros((c, self.histogram_bins),
                                           dtype=np.int64)

    def eval(self, labels, predictions, mask=None):
        labels = np.asarray(labels)
        predictions = np.asarray(predictions)
        if labels.ndim == 3:
            c = labels.shape[-1]
            labels = labels.reshape(-1, c)
            predictions = predictions.reshape(-1, c)
            if mask is not None:
                m = np.asarray(mask).reshape(-1).astype(bool)
                labels, predictions = labels[m], predictions[m]
        elif mask is not None:
            # 2-d path: [N] example mask — masked rows must not be binned
            m = np.asarray(mask).reshape(-1).astype(bool)
            labels, predictions = labels[m], predictions[m]
        self._ensure(labels.shape[-1])
        bins = np.clip((predictions * self.reliability_bins).astype(int), 0,
                       self.reliability_bins - 1)
        hbins = np.clip((predictions * self.histogram_bins).astype(int), 0,
                        self.histogram_bins - 1)
        residuals = np.abs(labels - predictions)
        rbins = np.clip((residuals * self.histogram_bins).astype(int), 0,
                        self.histogram_bins - 1)
        for c in range(labels.shape[-1]):
            np.add.at(self._bin_counts[c], bins[:, c], 1)
            np.add.at(self._bin_pos[c], bins[:, c], labels[:, c] >= 0.5)
            np.add.at(self._bin_prob_sum[c], bins[:, c], predictions[:, c])
            np.add.at(self._prob_hist[c], hbins[:, c], 1)
            np.add.at(self._residual_hist[c], rbins[:, c], 1)

    def reliability_diagram(self, cls: int):
        """Returns (mean_predicted_prob, observed_fraction) per bin."""
        counts = np.maximum(self._bin_counts[cls], 1)
        return (self._bin_prob_sum[cls] / counts, self._bin_pos[cls] / counts)

    def expected_calibration_error(self, cls: int) -> float:
        counts = self._bin_counts[cls]
        total = counts.sum()
        if not total:
            return 0.0
        mean_p, obs = self.reliability_diagram(cls)
        return float(np.sum(counts / total * np.abs(mean_p - obs)))

    def probability_histogram(self, cls: int):
        return self._prob_hist[cls].copy()

    # ---- serializable curve objects (reference getReliabilityDiagram /
    # getProbabilityHistogram return eval/curves types)
    def get_reliability_diagram(self, cls: int):
        from deeplearning4j_tpu.eval.curves import ReliabilityDiagram
        mean_p, obs = self.reliability_diagram(cls)
        return ReliabilityDiagram(f"class {cls}", mean_p, obs)

    def get_probability_histogram(self, cls: int):
        from deeplearning4j_tpu.eval.curves import Histogram
        return Histogram(f"P(class {cls})", 0.0, 1.0,
                         self._prob_hist[cls].copy())

    def get_residual_plot(self, cls: int):
        """|label − p| histogram (reference `getResidualPlot`)."""
        from deeplearning4j_tpu.eval.curves import Histogram
        return Histogram(f"|label - P| (class {cls})", 0.0, 1.0,
                         self._residual_hist[cls].copy())

    # ---- serde + merge ---------------------------------------------------
    _ACC_FIELDS = ("_bin_counts", "_bin_pos", "_bin_prob_sum", "_prob_hist",
                   "_residual_hist")

    def to_json(self) -> str:
        d = {"format_version": 1, "type": "EvaluationCalibration",
             "reliability_bins": self.reliability_bins,
             "histogram_bins": self.histogram_bins}
        for f in self._ACC_FIELDS:
            v = getattr(self, f)
            d[f] = None if v is None else v.tolist()
        return json.dumps(d)

    @classmethod
    def from_json(cls, s: str) -> "EvaluationCalibration":
        d = json.loads(s)
        check_payload_type(d, "EvaluationCalibration")
        ev = cls(reliability_bins=d["reliability_bins"],
                 histogram_bins=d["histogram_bins"])
        for f in cls._ACC_FIELDS:
            if d.get(f) is not None:
                arr = np.asarray(d[f])
                setattr(ev, f, arr.astype(
                    np.int64 if f != "_bin_prob_sum" else np.float64))
        return ev

    def merge(self, other: "EvaluationCalibration") -> "EvaluationCalibration":
        if other._bin_counts is None:
            return self
        if (other.reliability_bins != self.reliability_bins
                or other.histogram_bins != self.histogram_bins):
            raise ValueError("cannot merge calibrations with different bins")
        self._ensure(other._bin_counts.shape[0])
        for f in self._ACC_FIELDS:
            setattr(self, f, getattr(self, f) + getattr(other, f))
        return self
