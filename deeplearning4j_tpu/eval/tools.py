"""Evaluation rendering.

Reference: `deeplearning4j-core/evaluation/EvaluationTools.java`
(329 LoC): export ROC and calibration charts as self-contained HTML.
Charts here are inline SVG (no external assets), one file per export.
"""

from __future__ import annotations

from pathlib import Path

import numpy as np


def _svg_curve(xs, ys, *, width=480, height=400, label="", diagonal=True):
    pts = []
    for x, y in zip(xs, ys):
        px = 50 + float(x) * (width - 70)
        py = height - 40 - float(y) * (height - 70)
        pts.append(f"{px:.1f},{py:.1f}")
    diag = ""
    if diagonal:
        diag = (f'<line x1="50" y1="{height - 40}" x2="{width - 20}" y2="30" '
                f'stroke="#bbb" stroke-dasharray="4"/>')
    return (f'<svg width="{width}" height="{height}">'
            f'<rect width="{width}" height="{height}" fill="#fcfcfc" '
            f'stroke="#ddd"/>{diag}'
            f'<polyline fill="none" stroke="#c33" stroke-width="2" '
            f'points="{" ".join(pts)}"/>'
            f'<text x="55" y="20" font-size="13">{label}</text></svg>')


def _page(title: str, body: str) -> str:
    return (f"<!doctype html><html><head><title>{title}</title></head>"
            f"<body style='font-family:sans-serif'><h2>{title}</h2>"
            f"{body}</body></html>")


class EvaluationTools:
    @staticmethod
    def roc_chart_html(roc) -> str:
        """ROC → standalone HTML (reference `rocChartToHtml`)."""
        fpr, tpr = roc.get_roc_curve()
        auc = roc.calculate_auc()
        return _page("ROC curve",
                     _svg_curve(fpr, tpr, label=f"AUC = {auc:.4f}"))

    @staticmethod
    def export_roc_charts_to_html_file(roc, path):
        Path(path).write_text(EvaluationTools.roc_chart_html(roc))

    @staticmethod
    def calibration_chart_html(calibration, num_classes: int) -> str:
        parts = []
        for c in range(num_classes):
            mids, frac = calibration.reliability_diagram(c)
            ece = calibration.expected_calibration_error(c)
            parts.append(f"<h3>Class {c}</h3>")
            parts.append(_svg_curve(mids, frac,
                                    label=f"reliability (ECE {ece:.4f})"))
        return _page("Calibration", "".join(parts))

    @staticmethod
    def export_calibration_to_html_file(calibration, num_classes, path):
        Path(path).write_text(
            EvaluationTools.calibration_chart_html(calibration, num_classes))
