"""Vantage-point tree for metric-space kNN.

Reference: `clustering/vptree/VPTree.java` (parallel build, euclidean
default). Build: pick a vantage point, split remaining points at the
median distance; search prunes by the triangle inequality. Distances
over candidate leaves are computed with vectorised numpy (the
reference's parallel scalar loops → SIMD batch ops).
"""

from __future__ import annotations

import heapq
from typing import List, Optional, Tuple

import numpy as np


class _Node:
    __slots__ = ("index", "threshold", "inside", "outside")

    def __init__(self, index, threshold=0.0, inside=None, outside=None):
        self.index = index
        self.threshold = threshold
        self.inside = inside
        self.outside = outside


class VPTree:
    def __init__(self, points: np.ndarray, distance: str = "euclidean",
                 leaf_size: int = 32, seed: int = 0):
        self.items = np.asarray(points, np.float64)
        self.distance = distance
        self.leaf_size = leaf_size
        self._rng = np.random.default_rng(seed)
        idx = np.arange(len(self.items))
        self.root = self._build(idx)

    # ------------------------------------------------------------ metric
    def _dist(self, a: np.ndarray, b: np.ndarray) -> np.ndarray:
        if self.distance == "euclidean":
            return np.sqrt(np.sum((a - b) ** 2, axis=-1))
        if self.distance == "manhattan":
            return np.sum(np.abs(a - b), axis=-1)
        if self.distance == "cosine":
            na = np.linalg.norm(a, axis=-1)
            nb = np.linalg.norm(b, axis=-1)
            return 1.0 - np.sum(a * b, axis=-1) / np.clip(na * nb, 1e-12, None)
        raise ValueError(self.distance)

    # ------------------------------------------------------------- build
    def _build(self, idx: np.ndarray):
        if len(idx) == 0:
            return None
        if len(idx) <= self.leaf_size:
            return ("leaf", idx)
        vp_pos = int(self._rng.integers(len(idx)))
        vp = idx[vp_pos]
        rest = np.delete(idx, vp_pos)
        d = self._dist(self.items[rest], self.items[vp][None, :])
        med = float(np.median(d))
        inside = rest[d <= med]
        outside = rest[d > med]
        if len(inside) == 0 or len(outside) == 0:  # degenerate split
            return ("leaf", idx)
        node = _Node(vp, med)
        node.inside = self._build(inside)
        node.outside = self._build(outside)
        return node

    # ------------------------------------------------------------ search
    def knn(self, query, k: int) -> Tuple[List[int], List[float]]:
        """Returns (indices, distances) of the k nearest points."""
        query = np.asarray(query, np.float64)
        heap: List[Tuple[float, int]] = []  # max-heap via negated distance
        tau = [np.inf]

        def consider(indices):
            d = self._dist(self.items[indices], query[None, :])
            for di, ii in zip(d, indices):
                if len(heap) < k:
                    heapq.heappush(heap, (-di, int(ii)))
                    if len(heap) == k:
                        tau[0] = -heap[0][0]
                elif di < tau[0]:
                    heapq.heapreplace(heap, (-di, int(ii)))
                    tau[0] = -heap[0][0]

        def search(node):
            if node is None:
                return
            if isinstance(node, tuple):  # leaf
                consider(node[1])
                return
            d = float(self._dist(self.items[node.index][None, :], query[None, :])[0])
            consider(np.array([node.index]))
            if d <= node.threshold:
                search(node.inside)
                if d + tau[0] > node.threshold:
                    search(node.outside)
            else:
                search(node.outside)
                if d - tau[0] <= node.threshold:
                    search(node.inside)

        search(self.root)
        pairs = sorted(((-nd, i) for nd, i in heap))
        return [i for _, i in pairs], [d for d, _ in pairs]
