"""Nearest-neighbors + clustering library (reference:
deeplearning4j-nearestneighbors-parent, SURVEY §2.7): VPTree, KDTree,
QuadTree, SpTree (Barnes-Hut), k-means, and the REST server.
"""

from deeplearning4j_tpu.clustering.vptree import VPTree
from deeplearning4j_tpu.clustering.kdtree import KDTree
from deeplearning4j_tpu.clustering.quadtree import QuadTree
from deeplearning4j_tpu.clustering.sptree import SpTree
from deeplearning4j_tpu.clustering.kmeans import KMeansClustering, ClusterSet
