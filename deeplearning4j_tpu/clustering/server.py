"""Nearest-neighbors REST server + client.

Reference: `deeplearning4j-nearestneighbor-server/
server/NearestNeighborsServer.java:44` (Play router :191) — REST over a
VPTree with base64 NDArray DTOs. Here: stdlib http.server (the embedded
web server role Play fills in the reference) with JSON bodies:

POST /knn        {"index": i, "k": n}             → neighbors of a stored point
POST /knnnew     {"vector": [...], "k": n}        → neighbors of a new vector
GET  /healthz                                      → {"status": "ok"}
"""

from __future__ import annotations

import base64
import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional
from urllib import request as urlrequest

import numpy as np

from deeplearning4j_tpu.clustering.vptree import VPTree


def _decode_vector(payload) -> np.ndarray:
    """Accepts a JSON list or the reference's base64-float32 DTO."""
    if isinstance(payload, list):
        return np.asarray(payload, np.float32)
    if isinstance(payload, str):
        raw = base64.b64decode(payload)
        return np.frombuffer(raw, np.float32).copy()
    raise ValueError("vector must be a list or base64 string")


class NearestNeighborsServer:
    def __init__(self, points: np.ndarray, port: int = 0,
                 distance: str = "euclidean"):
        self.points = np.asarray(points, np.float32)
        self.tree = VPTree(self.points, distance=distance)
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):  # quiet
                pass

            def _json(self, code: int, obj):
                body = json.dumps(obj).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                if self.path == "/healthz":
                    self._json(200, {"status": "ok"})
                else:
                    self._json(404, {"error": "not found"})

            def do_POST(self):
                try:
                    length = int(self.headers.get("Content-Length", 0))
                    req = json.loads(self.rfile.read(length) or b"{}")
                    k = int(req.get("k", 5))
                    if self.path == "/knn":
                        idx = int(req["index"])
                        vec = outer.points[idx]
                    elif self.path == "/knnnew":
                        vec = _decode_vector(req["vector"])
                    else:
                        self._json(404, {"error": "not found"})
                        return
                    indices, dists = outer.tree.knn(vec, k)
                    self._json(200, {"results": [
                        {"index": int(i), "distance": float(d)}
                        for i, d in zip(indices, dists)]})
                except Exception as e:  # noqa: BLE001 — server boundary
                    self._json(400, {"error": str(e)})

        self._httpd = ThreadingHTTPServer(("127.0.0.1", port), Handler)
        self.port = self._httpd.server_address[1]
        self._thread: Optional[threading.Thread] = None

    def start(self):
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        daemon=True)
        self._thread.start()
        return self

    def stop(self):
        self._httpd.shutdown()
        self._httpd.server_close()


class NearestNeighborsClient:
    """Reference `deeplearning4j-nearestneighbors-client` equivalent."""

    def __init__(self, url: str):
        self.url = url.rstrip("/")

    def _post(self, path: str, payload: dict):
        req = urlrequest.Request(
            self.url + path, data=json.dumps(payload).encode(),
            headers={"Content-Type": "application/json"})
        with urlrequest.urlopen(req) as resp:  # noqa: S310 — localhost
            return json.loads(resp.read())

    def knn(self, index: int, k: int):
        return self._post("/knn", {"index": index, "k": k})

    def knn_new(self, vector, k: int):
        vec = np.asarray(vector, np.float32)
        payload = base64.b64encode(vec.tobytes()).decode()
        return self._post("/knnnew", {"vector": payload, "k": k})
