"""QuadTree for 2-D Barnes-Hut (reference `clustering/quadtree/
QuadTree.java`): 4-way spatial subdivision with center-of-mass
aggregation; used by 2-D t-SNE."""

from __future__ import annotations

from typing import Optional

import numpy as np


class QuadTree:
    MAX_DEPTH = 50

    def __init__(self, center_x: float, center_y: float,
                 half_w: float, half_h: float, depth: int = 0):
        self.cx, self.cy = center_x, center_y
        self.hw, self.hh = half_w, half_h
        self.depth = depth
        self.size = 0
        self.com = np.zeros(2)          # center of mass
        self.point: Optional[np.ndarray] = None
        self.index = -1
        self.children = None

    @staticmethod
    def build(points: np.ndarray) -> "QuadTree":
        points = np.asarray(points, np.float64)
        lo = points.min(axis=0)
        hi = points.max(axis=0)
        c = (lo + hi) / 2
        half = np.maximum((hi - lo) / 2, 1e-5) * 1.001
        tree = QuadTree(c[0], c[1], half[0], half[1])
        for i, p in enumerate(points):
            tree.insert(p, i)
        return tree

    def contains(self, p) -> bool:
        return (abs(p[0] - self.cx) <= self.hw + 1e-12
                and abs(p[1] - self.cy) <= self.hh + 1e-12)

    def _subdivide(self):
        hw, hh = self.hw / 2, self.hh / 2
        self.children = [
            QuadTree(self.cx - hw, self.cy - hh, hw, hh, self.depth + 1),
            QuadTree(self.cx + hw, self.cy - hh, hw, hh, self.depth + 1),
            QuadTree(self.cx - hw, self.cy + hh, hw, hh, self.depth + 1),
            QuadTree(self.cx + hw, self.cy + hh, hw, hh, self.depth + 1),
        ]

    def insert(self, p, index: int):
        p = np.asarray(p, np.float64)
        self.com = (self.com * self.size + p) / (self.size + 1)
        self.size += 1
        if self.size == 1 or self.depth >= self.MAX_DEPTH:
            if self.point is None:
                self.point = p
                self.index = index
            return
        if self.children is None:
            self._subdivide()
            old, oi = self.point, self.index
            self.point, self.index = None, -1
            if old is not None:
                self._child_for(old).insert(old, oi)
        self._child_for(p).insert(p, index)

    def _child_for(self, p):
        i = (1 if p[0] > self.cx else 0) + (2 if p[1] > self.cy else 0)
        return self.children[i]

    def compute_non_edge_forces(self, point, theta: float, neg_f: np.ndarray) -> float:
        """Barnes-Hut negative-force accumulation for t-SNE gradient;
        returns the sum contribution to Z."""
        if self.size == 0:
            return 0.0
        diff = point - self.com
        d2 = float(diff @ diff)
        max_width = max(self.hw, self.hh) * 2
        if self.children is None or max_width * max_width / max(d2, 1e-12) < theta * theta:
            if self.point is not None and np.allclose(self.com, point):
                return 0.0
            q = 1.0 / (1.0 + d2)
            mult = self.size * q
            neg_f += mult * q * diff
            return mult
        return sum(c.compute_non_edge_forces(point, theta, neg_f)
                   for c in self.children)
