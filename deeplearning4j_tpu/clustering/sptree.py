"""SpTree — k-dimensional Barnes-Hut tree (reference
`clustering/sptree/SpTree.java`, the dual-tree used by BarnesHutTsne):
2^d-way subdivision with center-of-mass aggregation and the same
non-edge-force accumulation as QuadTree, for arbitrary embedding dim.
"""

from __future__ import annotations

from typing import Optional

import numpy as np


class SpTree:
    MAX_DEPTH = 50

    def __init__(self, center: np.ndarray, half: np.ndarray, depth: int = 0):
        self.center = np.asarray(center, np.float64)
        self.half = np.asarray(half, np.float64)
        self.d = len(center)
        self.depth = depth
        self.size = 0
        self.com = np.zeros(self.d)
        self.point: Optional[np.ndarray] = None
        self.index = -1
        self.children = None

    @staticmethod
    def build(points: np.ndarray) -> "SpTree":
        points = np.asarray(points, np.float64)
        lo, hi = points.min(axis=0), points.max(axis=0)
        center = (lo + hi) / 2
        half = np.maximum((hi - lo) / 2, 1e-5) * 1.001
        tree = SpTree(center, half)
        for i, p in enumerate(points):
            tree.insert(p, i)
        return tree

    def _child_index(self, p) -> int:
        i = 0
        for ax in range(self.d):
            if p[ax] > self.center[ax]:
                i |= (1 << ax)
        return i

    def _subdivide(self):
        self.children = []
        half = self.half / 2
        for ci in range(1 << self.d):
            offset = np.array([half[ax] if (ci >> ax) & 1 else -half[ax]
                               for ax in range(self.d)])
            self.children.append(SpTree(self.center + offset, half,
                                        self.depth + 1))

    def insert(self, p, index: int):
        p = np.asarray(p, np.float64)
        self.com = (self.com * self.size + p) / (self.size + 1)
        self.size += 1
        if self.size == 1 or self.depth >= self.MAX_DEPTH:
            if self.point is None:
                self.point = p
                self.index = index
            return
        if self.children is None:
            self._subdivide()
            old, oi = self.point, self.index
            self.point, self.index = None, -1
            if old is not None:
                self.children[self._child_index(old)].insert(old, oi)
        self.children[self._child_index(p)].insert(p, index)

    def compute_non_edge_forces(self, point, theta: float, neg_f: np.ndarray) -> float:
        if self.size == 0:
            return 0.0
        diff = point - self.com
        d2 = float(diff @ diff)
        max_width = float(np.max(self.half)) * 2
        if self.children is None or max_width * max_width / max(d2, 1e-12) < theta * theta:
            if self.point is not None and np.allclose(self.com, point):
                return 0.0
            q = 1.0 / (1.0 + d2)
            mult = self.size * q
            neg_f += mult * q * diff
            return mult
        return sum(c.compute_non_edge_forces(point, theta, neg_f)
                   for c in self.children)
