"""K-means clustering.

Reference: `clustering/kmeans/KMeansClustering.java` + the generic
clustering framework (`algorithm/BaseClusteringAlgorithm`, strategies,
iteration conditions). TPU-first: each Lloyd iteration is ONE jitted
step — the [N, K] pairwise-distance block is a matmul on the MXU and
the centroid update a segment mean — instead of the reference's
per-point Java loops.
"""

from __future__ import annotations

from functools import partial
from typing import List, Optional

import jax
import jax.numpy as jnp
import numpy as np


@partial(jax.jit, donate_argnums=())
def _lloyd_step(points, centroids):
    # squared euclidean via (x-c)^2 = x^2 - 2xc + c^2; the cross term is
    # a single [N,D]x[D,K] matmul
    x2 = jnp.sum(points * points, axis=1, keepdims=True)
    c2 = jnp.sum(centroids * centroids, axis=1)[None, :]
    d2 = x2 - 2.0 * points @ centroids.T + c2
    assign = jnp.argmin(d2, axis=1)
    K = centroids.shape[0]
    one_hot = jax.nn.one_hot(assign, K, dtype=points.dtype)      # [N,K]
    counts = jnp.sum(one_hot, axis=0)                            # [K]
    sums = one_hot.T @ points                                    # [K,D]
    new_centroids = jnp.where(counts[:, None] > 0,
                              sums / jnp.clip(counts[:, None], 1.0, None),
                              centroids)
    cost = jnp.sum(jnp.min(d2, axis=1))
    return new_centroids, assign, cost


class Cluster:
    def __init__(self, center: np.ndarray, points: List[int]):
        self.center = center
        self.points = points


class ClusterSet:
    def __init__(self, centroids: np.ndarray, assignments: np.ndarray,
                 cost: float):
        self.centroids = centroids
        self.assignments = assignments
        self.cost = cost

    def get_clusters(self) -> List[Cluster]:
        return [Cluster(self.centroids[k],
                        list(np.nonzero(self.assignments == k)[0]))
                for k in range(len(self.centroids))]

    def nearest_cluster(self, point) -> int:
        d = np.sum((self.centroids - np.asarray(point)[None, :]) ** 2, axis=1)
        return int(np.argmin(d))


class KMeansClustering:
    """`KMeansClustering.setup(k, maxIterations, distance)` equivalent."""

    def __init__(self, k: int, max_iterations: int = 100,
                 min_delta: float = 1e-6, seed: int = 0):
        self.k = k
        self.max_iterations = max_iterations
        self.min_delta = min_delta
        self.seed = seed

    def apply_to(self, points: np.ndarray) -> ClusterSet:
        points = np.asarray(points, np.float32)
        rng = np.random.default_rng(self.seed)
        # k-means++ style init: spread starting centroids
        centroids = [points[rng.integers(len(points))]]
        for _ in range(1, self.k):
            d2 = np.min(
                [np.sum((points - c[None, :]) ** 2, axis=1) for c in centroids],
                axis=0)
            probs = d2 / max(d2.sum(), 1e-12)
            centroids.append(points[rng.choice(len(points), p=probs)])
        centroids = jnp.asarray(np.stack(centroids))
        pts = jnp.asarray(points)
        prev_cost = np.inf
        assign = None
        cost = np.inf
        for _ in range(self.max_iterations):
            centroids, assign, cost = _lloyd_step(pts, centroids)
            cost = float(cost)
            if abs(prev_cost - cost) < self.min_delta:
                break
            prev_cost = cost
        return ClusterSet(np.asarray(centroids), np.asarray(assign), cost)
