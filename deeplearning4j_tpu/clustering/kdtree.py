"""KD-tree (reference `clustering/kdtree/KDTree.java`): axis-cycled
median build, kNN + range queries with hyperplane pruning."""

from __future__ import annotations

import heapq
from typing import List, Tuple

import numpy as np


class _KDNode:
    __slots__ = ("index", "axis", "left", "right")

    def __init__(self, index, axis):
        self.index = index
        self.axis = axis
        self.left = None
        self.right = None


class KDTree:
    def __init__(self, points: np.ndarray):
        self.items = np.asarray(points, np.float64)
        self.dims = self.items.shape[1]
        self.root = self._build(np.arange(len(self.items)), 0)

    def _build(self, idx: np.ndarray, depth: int):
        if len(idx) == 0:
            return None
        axis = depth % self.dims
        order = np.argsort(self.items[idx, axis])
        mid = len(idx) // 2
        node = _KDNode(int(idx[order[mid]]), axis)
        node.left = self._build(idx[order[:mid]], depth + 1)
        node.right = self._build(idx[order[mid + 1:]], depth + 1)
        return node

    def knn(self, query, k: int) -> Tuple[List[int], List[float]]:
        query = np.asarray(query, np.float64)
        heap: List[Tuple[float, int]] = []

        def search(node):
            if node is None:
                return
            p = self.items[node.index]
            d = float(np.sqrt(np.sum((p - query) ** 2)))
            if len(heap) < k:
                heapq.heappush(heap, (-d, node.index))
            elif d < -heap[0][0]:
                heapq.heapreplace(heap, (-d, node.index))
            diff = query[node.axis] - p[node.axis]
            near, far = (node.left, node.right) if diff <= 0 else (node.right, node.left)
            search(near)
            if len(heap) < k or abs(diff) < -heap[0][0]:
                search(far)

        search(self.root)
        pairs = sorted(((-nd, i) for nd, i in heap))
        return [i for _, i in pairs], [d for d, _ in pairs]

    def range(self, lower, upper) -> List[int]:
        """All points inside the axis-aligned box [lower, upper]
        (reference KDTree range query)."""
        lower = np.asarray(lower, np.float64)
        upper = np.asarray(upper, np.float64)
        out: List[int] = []

        def search(node):
            if node is None:
                return
            p = self.items[node.index]
            if np.all(p >= lower) and np.all(p <= upper):
                out.append(node.index)
            if p[node.axis] >= lower[node.axis]:
                search(node.left)
            if p[node.axis] <= upper[node.axis]:
                search(node.right)

        search(self.root)
        return out
