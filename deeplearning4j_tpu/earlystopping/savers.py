"""Model savers (reference `earlystopping/saver/InMemoryModelSaver.java`,
`LocalFileModelSaver.java`)."""

from __future__ import annotations

from pathlib import Path

from deeplearning4j_tpu.util.serializer import ModelSerializer


class InMemoryModelSaver:
    def __init__(self):
        self._best = None
        self._latest = None

    def save_best_model(self, model, score):
        self._best = model.copy() if hasattr(model, "copy") else model

    def save_latest_model(self, model, score):
        self._latest = model.copy() if hasattr(model, "copy") else model

    def get_best_model(self):
        return self._best

    def get_latest_model(self):
        return self._latest


class LocalFileModelSaver:
    def __init__(self, directory):
        self.directory = Path(directory)
        self.directory.mkdir(parents=True, exist_ok=True)

    @property
    def best_path(self):
        return self.directory / "bestModel.zip"

    @property
    def latest_path(self):
        return self.directory / "latestModel.zip"

    def save_best_model(self, model, score):
        ModelSerializer.write_model(model, self.best_path)

    def save_latest_model(self, model, score):
        ModelSerializer.write_model(model, self.latest_path)

    def get_best_model(self):
        return ModelSerializer.restore_model(self.best_path)

    def get_latest_model(self):
        return ModelSerializer.restore_model(self.latest_path)
