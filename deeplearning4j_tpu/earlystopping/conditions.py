"""Termination conditions (reference `earlystopping/termination/`):
MaxEpochs, ScoreImprovementEpoch, MaxScoreIteration, MaxTimeIteration,
InvalidScore (NaN guard — the reference's divergence detector,
`InvalidScoreIterationTerminationCondition.java`)."""

from __future__ import annotations

import math
import time


class EpochTerminationCondition:
    def initialize(self):
        pass

    def terminate(self, epoch: int, score: float) -> bool:
        raise NotImplementedError


class IterationTerminationCondition:
    def initialize(self):
        pass

    def terminate(self, last_score: float) -> bool:
        raise NotImplementedError


class MaxEpochsTerminationCondition(EpochTerminationCondition):
    def __init__(self, max_epochs: int):
        self.max_epochs = max_epochs

    def terminate(self, epoch, score):
        return epoch + 1 >= self.max_epochs

    def __str__(self):
        return f"MaxEpochs({self.max_epochs})"


class ScoreImprovementEpochTerminationCondition(EpochTerminationCondition):
    """Stop after N epochs without score improvement (reference
    `ScoreImprovementEpochTerminationCondition.java`)."""

    def __init__(self, max_epochs_without_improvement: int, min_improvement: float = 0.0):
        self.patience = max_epochs_without_improvement
        self.min_improvement = min_improvement
        self.best = math.inf
        self.since = 0

    def initialize(self):
        self.best = math.inf
        self.since = 0

    def terminate(self, epoch, score):
        if score < self.best - self.min_improvement:
            self.best = score
            self.since = 0
            return False
        self.since += 1
        return self.since > self.patience

    def __str__(self):
        return f"ScoreImprovement(patience={self.patience})"


class MaxScoreIterationTerminationCondition(IterationTerminationCondition):
    def __init__(self, max_score: float):
        self.max_score = max_score

    def terminate(self, last_score):
        return last_score > self.max_score

    def __str__(self):
        return f"MaxScore({self.max_score})"


class MaxTimeIterationTerminationCondition(IterationTerminationCondition):
    def __init__(self, max_seconds: float):
        self.max_seconds = max_seconds
        self._start = None

    def initialize(self):
        self._start = time.monotonic()

    def terminate(self, last_score):
        if self._start is None:
            self._start = time.monotonic()
        return (time.monotonic() - self._start) > self.max_seconds

    def __str__(self):
        return f"MaxTime({self.max_seconds}s)"


class InvalidScoreIterationTerminationCondition(IterationTerminationCondition):
    """NaN/Inf divergence guard."""

    def terminate(self, last_score):
        return math.isnan(last_score) or math.isinf(last_score)

    def __str__(self):
        return "InvalidScore()"
