"""Early stopping (reference: `earlystopping/`): configuration,
termination conditions, score calculators, model savers, trainer.
"""

from deeplearning4j_tpu.earlystopping.config import (
    EarlyStoppingConfiguration,
    EarlyStoppingResult,
)
from deeplearning4j_tpu.earlystopping.conditions import (
    MaxEpochsTerminationCondition,
    MaxScoreIterationTerminationCondition,
    MaxTimeIterationTerminationCondition,
    ScoreImprovementEpochTerminationCondition,
    InvalidScoreIterationTerminationCondition,
)
from deeplearning4j_tpu.earlystopping.savers import (
    InMemoryModelSaver,
    LocalFileModelSaver,
)
from deeplearning4j_tpu.earlystopping.scorecalc import DataSetLossCalculator
from deeplearning4j_tpu.earlystopping.trainer import EarlyStoppingTrainer
