"""Score calculators (reference `earlystopping/scorecalc/
DataSetLossCalculator.java`): loss over a held-out iterator."""

from __future__ import annotations

from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.datasets.iterator import DataSetIterator, as_iterator


class DataSetLossCalculator:
    def __init__(self, iterator, average: bool = True):
        self.iterator = as_iterator(iterator)
        self.average = average

    def calculate_score(self, model) -> float:
        total, n = 0.0, 0
        self.iterator.reset()
        for ds in self.iterator:
            b = ds.num_examples()
            total += model.score(ds) * (b if self.average else 1.0)
            n += b if self.average else 1
        return total / max(n, 1)


class ClassificationScoreCalculator:
    """Score = 1 - accuracy so 'lower is better' holds uniformly."""

    def __init__(self, iterator):
        self.iterator = as_iterator(iterator)

    def calculate_score(self, model) -> float:
        e = model.evaluate(self.iterator)
        return 1.0 - e.accuracy()
