"""EarlyStoppingTrainer (reference
`earlystopping/trainer/EarlyStoppingTrainer.java`): epoch loop →
score on holdout every N epochs → keep best model → stop on
termination conditions (incl. per-iteration NaN guard via listener)."""

from __future__ import annotations

import math
from typing import Optional

from deeplearning4j_tpu.earlystopping.config import (
    EarlyStoppingConfiguration,
    EarlyStoppingResult,
    TerminationReason,
)
from deeplearning4j_tpu.optimize.listeners import TrainingListener


class _IterationGuard(TrainingListener):
    def __init__(self, conditions):
        self.conditions = conditions
        self.triggered: Optional[str] = None

    def iteration_done(self, model, iteration, epoch, score, **info):
        if self.triggered:
            return
        for c in self.conditions:
            if c.terminate(score):
                self.triggered = str(c)


class EarlyStoppingTrainer:
    def __init__(self, config: EarlyStoppingConfiguration, model, train_data):
        self.config = config
        self.model = model
        self.train_data = train_data

    def fit(self) -> EarlyStoppingResult:
        cfg = self.config
        for c in cfg.epoch_termination_conditions:
            c.initialize()
        for c in cfg.iteration_termination_conditions:
            c.initialize()
        guard = _IterationGuard(cfg.iteration_termination_conditions)
        self.model.listeners = list(self.model.listeners) + [guard]

        best_score, best_epoch = math.inf, -1
        score_vs_epoch = {}
        epoch = 0
        reason = TerminationReason.MAX_EPOCHS
        details = "no termination condition triggered"
        while True:
            self.model.fit(self.train_data, epochs=1)
            if guard.triggered:
                reason = TerminationReason.ITERATION_TERMINATION
                details = guard.triggered
                break
            if epoch % cfg.evaluate_every_n_epochs == 0:
                score = (cfg.score_calculator.calculate_score(self.model)
                         if cfg.score_calculator else self.model.score())
                score_vs_epoch[epoch] = score
                if score < best_score:
                    best_score, best_epoch = score, epoch
                    if cfg.model_saver:
                        cfg.model_saver.save_best_model(self.model, score)
                if cfg.save_last_model and cfg.model_saver:
                    cfg.model_saver.save_latest_model(self.model, score)
            stop = False
            last = score_vs_epoch.get(epoch, self.model.score())
            for c in cfg.epoch_termination_conditions:
                if c.terminate(epoch, last):
                    reason = TerminationReason.EPOCH_TERMINATION
                    details = str(c)
                    stop = True
                    break
            if stop:
                break
            epoch += 1

        best_model = (cfg.model_saver.get_best_model()
                      if cfg.model_saver and best_epoch >= 0 else self.model)
        self.model.listeners = [l for l in self.model.listeners if l is not guard]
        return EarlyStoppingResult(
            termination_reason=reason,
            termination_details=details,
            score_vs_epoch=score_vs_epoch,
            best_model_epoch=best_epoch,
            best_model_score=best_score,
            total_epochs=epoch + 1,
            best_model=best_model,
        )


# Graph models use the same trainer (the reference's
# EarlyStoppingGraphTrainer only differs in Java generics).
EarlyStoppingGraphTrainer = EarlyStoppingTrainer


class EarlyStoppingParallelTrainer(EarlyStoppingTrainer):
    """Early stopping over multi-device training (reference
    `parallelism/EarlyStoppingParallelTrainer.java`, 362 LoC): each
    epoch runs through a ParallelTrainer on the mesh instead of the
    single-device fit; scoring/saving/termination logic is inherited."""

    def __init__(self, config, model, train_data, mesh=None, *,
                 mode: str = "sync", averaging_frequency: int = 5,
                 batch_size: int = 32):
        super().__init__(config, model, train_data)
        from deeplearning4j_tpu.parallel.trainer import ParallelTrainer
        self._trainer = ParallelTrainer(model, mesh, mode=mode,
                                        averaging_frequency=averaging_frequency)
        self._batch_size = batch_size
        # route the per-epoch fit through the parallel engine
        self.model = _ParallelFitAdapter(model, self._trainer, batch_size)


class _ParallelFitAdapter:
    """Delegates everything to the wrapped model but fits via the
    ParallelTrainer (so EarlyStoppingTrainer's loop is unchanged)."""

    def __init__(self, model, trainer, batch_size):
        self._model = model
        self._trainer = trainer
        self._batch_size = batch_size

    def fit(self, data, epochs=1, **kw):
        self._trainer.fit(data, epochs=epochs,
                          batch_size=kw.get("batch_size", self._batch_size))
        return self._model

    def __getattr__(self, name):
        return getattr(self._model, name)

    @property
    def listeners(self):
        return self._model.listeners

    @listeners.setter
    def listeners(self, v):
        self._model.listeners = v
