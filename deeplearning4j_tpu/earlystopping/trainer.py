"""EarlyStoppingTrainer (reference
`earlystopping/trainer/EarlyStoppingTrainer.java`): epoch loop →
score on holdout every N epochs → keep best model → stop on
termination conditions (incl. per-iteration NaN guard via listener)."""

from __future__ import annotations

import math
from typing import Optional

from deeplearning4j_tpu.earlystopping.config import (
    EarlyStoppingConfiguration,
    EarlyStoppingResult,
    TerminationReason,
)
from deeplearning4j_tpu.optimize.listeners import TrainingListener


class _IterationGuard(TrainingListener):
    def __init__(self, conditions):
        self.conditions = conditions
        self.triggered: Optional[str] = None

    def iteration_done(self, model, iteration, epoch, score, **info):
        if self.triggered:
            return
        for c in self.conditions:
            if c.terminate(score):
                self.triggered = str(c)


class EarlyStoppingTrainer:
    def __init__(self, config: EarlyStoppingConfiguration, model, train_data,
                 checkpointer=None):
        """`checkpointer`: optional AsyncCheckpointer (or directory
        path) — persists the FULL training state plus the early-stopping
        trackers (best score/epoch, epochs-without-improvement, score
        history) and the best model's arrays after every evaluated
        epoch, so an early-stopped run is resumable with
        `fit(resume=True)` (fault/ runtime)."""
        self.config = config
        self.model = model
        self.train_data = train_data
        if checkpointer is not None:
            from deeplearning4j_tpu.fault import AsyncCheckpointer
            if not isinstance(checkpointer, AsyncCheckpointer):
                checkpointer = AsyncCheckpointer(checkpointer)
        self.checkpointer = checkpointer

    # --------------------------------------------------- fault persistence
    def _capture_best(self):
        """Host snapshot of the current (new-best) model arrays."""
        from deeplearning4j_tpu.fault import state as fs
        return {
            "params": fs.unflatten_arrays(fs.flatten_arrays(
                self.model.params)),
            "net_state": fs.unflatten_arrays(fs.flatten_arrays(
                self.model.net_state)) if self.model.net_state else {},
            "updater_state": fs.unflatten_arrays(fs.flatten_arrays(
                self.model.updater_state)),
        }

    def _save_checkpoint(self, epoch, best_score, best_epoch,
                         score_vs_epoch, best_arrays):
        from deeplearning4j_tpu.fault import capture_training_state
        state = capture_training_state(
            self.model,
            iterator=(self.train_data
                      if hasattr(self.train_data, "cursor") else None),
            extra_meta={"earlystopping": {
                "epoch": epoch,
                "best_score": (None if math.isinf(best_score)
                               else float(best_score)),
                "best_epoch": best_epoch,
                "epochs_since_best": epoch - best_epoch,
                "score_vs_epoch": {str(k): float(v)
                                   for k, v in score_vs_epoch.items()},
            }})
        # the best arrays ride EVERY checkpoint on purpose: retention GC
        # may delete the checkpoint where the best was first recorded,
        # and resume reads only the newest valid one (the arrays are a
        # one-time host snapshot — per-save cost is the extra npz bytes)
        if best_arrays is not None:
            state["arrays"]["es_best"] = best_arrays
        self.checkpointer.save(state, int(self.model.iteration_count))

    def _model_from_arrays(self, arrays):
        from deeplearning4j_tpu.fault import state as fs
        m = fs.build_model({"model_type": type(self.model).__name__,
                            "configuration": self.model.conf.to_dict()})
        fs.restore_training_state(
            m, {"arrays": arrays,
                "meta": {"iteration_count": 0, "epoch_count": 0}})
        return m

    def fit(self, resume: bool = False) -> EarlyStoppingResult:
        cfg = self.config
        for c in cfg.epoch_termination_conditions:
            c.initialize()
        for c in cfg.iteration_termination_conditions:
            c.initialize()
        guard = _IterationGuard(cfg.iteration_termination_conditions)
        self.model.listeners = list(self.model.listeners) + [guard]

        best_score, best_epoch = math.inf, -1
        best_arrays = None
        score_vs_epoch = {}
        epoch = 0
        if resume:
            if self.checkpointer is None:
                raise ValueError(
                    "fit(resume=True) needs a checkpointer; construct "
                    "EarlyStoppingTrainer(..., checkpointer=dir)")
            from deeplearning4j_tpu.fault import (
                load_latest_valid,
                restore_training_state,
            )
            try:
                state, _ = load_latest_valid(self.checkpointer.directory)
            except FileNotFoundError:
                state = None      # nothing saved yet: cold start
            if state is not None:
                restore_training_state(self.model, state)
                es = state["meta"].get("earlystopping") or {}
                if es.get("best_score") is not None:
                    best_score = float(es["best_score"])
                best_epoch = int(es.get("best_epoch", -1))
                score_vs_epoch = {int(k): v for k, v in
                                  (es.get("score_vs_epoch") or {}).items()}
                epoch = int(es.get("epoch", -1)) + 1
                # trajectory parity: the checkpoint was taken at an
                # epoch END, so the iterator must continue at the NEXT
                # pass of the same shuffle stream (not replay the
                # completed pass, not restart the stream at pass 0)
                cur = state["meta"].get("iterator")
                if cur is not None:
                    try:
                        self.train_data.seek({"epoch": epoch, "batch": 0,
                                              "seed": cur.get("seed"),
                                              "shuffle": cur.get("shuffle")})
                    except NotImplementedError:
                        pass   # source without the position contract
                best_arrays = state["arrays"].get("es_best")
                if best_arrays is not None and cfg.model_saver:
                    cfg.model_saver.save_best_model(
                        self._model_from_arrays(best_arrays), best_score)
        reason = TerminationReason.MAX_EPOCHS
        details = "no termination condition triggered"
        while True:
            self.model.fit(self.train_data, epochs=1)
            if guard.triggered:
                reason = TerminationReason.ITERATION_TERMINATION
                details = guard.triggered
                break
            if epoch % cfg.evaluate_every_n_epochs == 0:
                score = (cfg.score_calculator.calculate_score(self.model)
                         if cfg.score_calculator else self.model.score())
                score_vs_epoch[epoch] = score
                if score < best_score:
                    best_score, best_epoch = score, epoch
                    if cfg.model_saver:
                        cfg.model_saver.save_best_model(self.model, score)
                    if self.checkpointer is not None:
                        best_arrays = self._capture_best()
                if cfg.save_last_model and cfg.model_saver:
                    cfg.model_saver.save_latest_model(self.model, score)
                if self.checkpointer is not None:
                    self._save_checkpoint(epoch, best_score, best_epoch,
                                          score_vs_epoch, best_arrays)
            stop = False
            last = score_vs_epoch.get(epoch, self.model.score())
            for c in cfg.epoch_termination_conditions:
                if c.terminate(epoch, last):
                    reason = TerminationReason.EPOCH_TERMINATION
                    details = str(c)
                    stop = True
                    break
            if stop:
                break
            epoch += 1

        if self.checkpointer is not None:
            self.checkpointer.wait()   # durable before reporting done
        if cfg.model_saver and best_epoch >= 0:
            best_model = cfg.model_saver.get_best_model()
        elif best_arrays is not None and best_epoch >= 0:
            # no saver configured but the checkpointer kept the best
            # arrays (a resumed run's best may predate this process)
            best_model = self._model_from_arrays(best_arrays)
        else:
            best_model = self.model
        self.model.listeners = [l for l in self.model.listeners if l is not guard]
        return EarlyStoppingResult(
            termination_reason=reason,
            termination_details=details,
            score_vs_epoch=score_vs_epoch,
            best_model_epoch=best_epoch,
            best_model_score=best_score,
            total_epochs=epoch + 1,
            best_model=best_model,
        )


# Graph models use the same trainer (the reference's
# EarlyStoppingGraphTrainer only differs in Java generics).
EarlyStoppingGraphTrainer = EarlyStoppingTrainer


class EarlyStoppingParallelTrainer(EarlyStoppingTrainer):
    """Early stopping over multi-device training (reference
    `parallelism/EarlyStoppingParallelTrainer.java`, 362 LoC): each
    epoch runs through a ParallelTrainer on the mesh instead of the
    single-device fit; scoring/saving/termination logic is inherited."""

    def __init__(self, config, model, train_data, mesh=None, *,
                 mode: str = "sync", averaging_frequency: int = 5,
                 batch_size: int = 32):
        super().__init__(config, model, train_data)
        from deeplearning4j_tpu.parallel.trainer import ParallelTrainer
        self._trainer = ParallelTrainer(model, mesh, mode=mode,
                                        averaging_frequency=averaging_frequency)
        self._batch_size = batch_size
        # route the per-epoch fit through the parallel engine
        self.model = _ParallelFitAdapter(model, self._trainer, batch_size)


class _ParallelFitAdapter:
    """Delegates everything to the wrapped model but fits via the
    ParallelTrainer (so EarlyStoppingTrainer's loop is unchanged)."""

    def __init__(self, model, trainer, batch_size):
        self._model = model
        self._trainer = trainer
        self._batch_size = batch_size

    def fit(self, data, epochs=1, **kw):
        self._trainer.fit(data, epochs=epochs,
                          batch_size=kw.get("batch_size", self._batch_size))
        return self._model

    def __getattr__(self, name):
        return getattr(self._model, name)

    @property
    def listeners(self):
        return self._model.listeners

    @listeners.setter
    def listeners(self, v):
        self._model.listeners = v
