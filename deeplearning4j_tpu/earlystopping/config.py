"""EarlyStoppingConfiguration + result (reference
`earlystopping/EarlyStoppingConfiguration.java`,
`EarlyStoppingResult.java`)."""

from __future__ import annotations

import dataclasses
from enum import Enum
from typing import Any, List, Optional


class TerminationReason(str, Enum):
    EPOCH_TERMINATION = "epoch_termination"
    ITERATION_TERMINATION = "iteration_termination"
    MAX_EPOCHS = "max_epochs"
    ERROR = "error"


@dataclasses.dataclass
class EarlyStoppingConfiguration:
    score_calculator: Any = None
    model_saver: Any = None
    epoch_termination_conditions: List = dataclasses.field(default_factory=list)
    iteration_termination_conditions: List = dataclasses.field(default_factory=list)
    evaluate_every_n_epochs: int = 1
    save_last_model: bool = False


@dataclasses.dataclass
class EarlyStoppingResult:
    termination_reason: TerminationReason
    termination_details: str
    score_vs_epoch: dict
    best_model_epoch: int
    best_model_score: float
    total_epochs: int
    best_model: Any = None
