"""t-SNE embeddings.

Reference: `plot/Tsne.java` (423 LoC, exact O(N^2)) and
`plot/BarnesHutTsne.java` (868 LoC, O(N log N) with SpTree).

TPU-first split: the exact variant runs FULLY jitted — the [N,N]
affinity and gradient blocks are dense matmul/elementwise work that XLA
maps straight onto the MXU, practical into the tens of thousands of
points; Barnes-Hut remains a host (numpy + SpTree) algorithm because
adaptive tree traversal does not map to static-shape XLA — same
capability split the reference has (Java loops there, jit here).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.clustering.sptree import SpTree


from deeplearning4j_tpu.nd.donation import jit_donated as _jit_donated


def _binary_search_perplexity(d2_row: np.ndarray, perplexity: float,
                              tol: float = 1e-5, max_iter: int = 50):
    """Find beta (1/2sigma^2) giving the target perplexity (reference
    Tsne.hBeta / d2p binary search)."""
    beta, beta_min, beta_max = 1.0, -np.inf, np.inf
    target = np.log(perplexity)
    p = np.zeros_like(d2_row)
    for _ in range(max_iter):
        p = np.exp(-d2_row * beta)
        s = p.sum()
        if s <= 0:
            s = 1e-12
        h = np.log(s) + beta * np.sum(d2_row * p) / s
        p = p / s
        diff = h - target
        if abs(diff) < tol:
            break
        if diff > 0:
            beta_min = beta
            beta = beta * 2 if beta_max == np.inf else (beta + beta_max) / 2
        else:
            beta_max = beta
            beta = beta / 2 if beta_min == -np.inf else (beta + beta_min) / 2
    return p


def _compute_p(x: np.ndarray, perplexity: float) -> np.ndarray:
    n = len(x)
    sum_x = np.sum(x * x, axis=1)
    d2 = np.maximum(sum_x[:, None] - 2 * x @ x.T + sum_x[None, :], 0.0)
    p = np.zeros((n, n))
    for i in range(n):
        row = np.delete(d2[i], i)
        pi = _binary_search_perplexity(row, perplexity)
        p[i, np.arange(n) != i] = pi
    p = (p + p.T) / (2 * n)
    return np.maximum(p, 1e-12)


@_jit_donated(donate=(1, 2, 3))
def _tsne_step(p, y, velocity, gains, momentum, lr):
    """One exact t-SNE gradient step (jitted: [N,N] blocks on device)."""
    sum_y = jnp.sum(y * y, axis=1)
    num = 1.0 / (1.0 + sum_y[:, None] - 2.0 * y @ y.T + sum_y[None, :])
    num = num * (1.0 - jnp.eye(y.shape[0], dtype=y.dtype))
    q = jnp.maximum(num / jnp.sum(num), 1e-12)
    pq = (p - q) * num
    grad = 4.0 * ((jnp.diag(jnp.sum(pq, axis=1)) - pq) @ y)
    gains = jnp.where(jnp.sign(grad) != jnp.sign(velocity),
                      gains + 0.2, gains * 0.8)
    gains = jnp.maximum(gains, 0.01)
    velocity = momentum * velocity - lr * gains * grad
    y = y + velocity
    y = y - jnp.mean(y, axis=0, keepdims=True)
    kl = jnp.sum(p * jnp.log(p / q))
    return y, velocity, gains, kl


class Tsne:
    """Exact t-SNE (reference `Tsne.java`), jitted per-iteration."""

    def __init__(self, n_components: int = 2, perplexity: float = 30.0,
                 learning_rate: float = 200.0, n_iter: int = 500,
                 momentum: float = 0.5, final_momentum: float = 0.8,
                 early_exaggeration: float = 12.0, seed: int = 0):
        self.n_components = n_components
        self.perplexity = perplexity
        self.learning_rate = learning_rate
        self.n_iter = n_iter
        self.momentum = momentum
        self.final_momentum = final_momentum
        self.early_exaggeration = early_exaggeration
        self.seed = seed
        self.kl_divergence_: Optional[float] = None

    def fit_transform(self, x: np.ndarray) -> np.ndarray:
        x = np.asarray(x, np.float64)
        n = len(x)
        perp = min(self.perplexity, max((n - 1) / 3.0, 1.0))
        p = _compute_p(x, perp)
        rng = np.random.default_rng(self.seed)
        y = jnp.asarray(rng.standard_normal((n, self.n_components)) * 1e-4)
        velocity = jnp.zeros_like(y)
        gains = jnp.ones_like(y)
        p_dev = jnp.asarray(p)
        exag_end = min(100, self.n_iter // 4)
        kl = None
        for it in range(self.n_iter):
            mom = self.momentum if it < 250 else self.final_momentum
            p_it = p_dev * self.early_exaggeration if it < exag_end else p_dev
            y, velocity, gains, kl = _tsne_step(
                p_it, y, velocity, gains,
                jnp.float64(mom) if y.dtype == jnp.float64 else np.float32(mom),
                np.float32(self.learning_rate))
        self.kl_divergence_ = float(kl)
        return np.asarray(y)


class BarnesHutTsne(Tsne):
    """Barnes-Hut t-SNE (reference `BarnesHutTsne.java`): sparse input
    affinities from a kNN graph, SpTree-approximated repulsive forces."""

    def __init__(self, theta: float = 0.5, **kw):
        super().__init__(**kw)
        self.theta = theta

    def fit_transform(self, x: np.ndarray) -> np.ndarray:
        x = np.asarray(x, np.float64)
        n = len(x)
        if n <= 512 or self.theta <= 0:
            return super().fit_transform(x)  # exact is fine (and jitted)
        perp = min(self.perplexity, (n - 1) / 3.0)
        k = int(min(n - 1, 3 * perp))
        # kNN graph via brute-force blocked distances (vectorised)
        sum_x = np.sum(x * x, axis=1)
        d2 = np.maximum(sum_x[:, None] - 2 * x @ x.T + sum_x[None, :], 0.0)
        np.fill_diagonal(d2, np.inf)
        nn_idx = np.argpartition(d2, k, axis=1)[:, :k]
        rows = np.repeat(np.arange(n), k)
        cols = nn_idx.ravel()
        p_vals = np.zeros(n * k)
        for i in range(n):
            p_vals[i * k:(i + 1) * k] = _binary_search_perplexity(
                d2[i, nn_idx[i]], perp)
        # symmetrize the sparse P
        pmat = {}
        for r, c, v in zip(rows, cols, p_vals):
            pmat[(r, c)] = pmat.get((r, c), 0.0) + v
            pmat[(c, r)] = pmat.get((c, r), 0.0) + v
        total = sum(pmat.values())
        sp_rows = np.array([rc[0] for rc in pmat])
        sp_cols = np.array([rc[1] for rc in pmat])
        sp_vals = np.array(list(pmat.values())) / total

        rng = np.random.default_rng(self.seed)
        y = rng.standard_normal((n, self.n_components)) * 1e-4
        velocity = np.zeros_like(y)
        gains = np.ones_like(y)
        exag_end = min(100, self.n_iter // 4)
        for it in range(self.n_iter):
            mom = self.momentum if it < 250 else self.final_momentum
            exag = self.early_exaggeration if it < exag_end else 1.0
            tree = SpTree.build(y)
            neg = np.zeros_like(y)
            z = 0.0
            for i in range(n):
                f = np.zeros(self.n_components)
                z += tree.compute_non_edge_forces(y[i], self.theta, f)
                neg[i] = f
            diff = y[sp_rows] - y[sp_cols]
            q_num = 1.0 / (1.0 + np.sum(diff * diff, axis=1))
            attr = np.zeros_like(y)
            np.add.at(attr, sp_rows, (exag * sp_vals * q_num)[:, None] * diff)
            grad = attr - neg / max(z, 1e-12)
            gains = np.where(np.sign(grad) != np.sign(velocity),
                             gains + 0.2, gains * 0.8)
            gains = np.maximum(gains, 0.01)
            velocity = mom * velocity - self.learning_rate * gains * grad
            y = y + velocity
            y = y - y.mean(axis=0, keepdims=True)
        return y
