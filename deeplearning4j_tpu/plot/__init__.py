"""Embedding visualization (reference: deeplearning4j-core `plot/`)."""

from deeplearning4j_tpu.plot.tsne import BarnesHutTsne, Tsne
