"""CSV → DataSet conversion for streams (reference dl4j-streaming's
Camel CSV route feeding DataSets)."""

from __future__ import annotations

from typing import Optional

import numpy as np

from deeplearning4j_tpu.datasets.dataset import DataSet


def csv_to_dataset(lines, label_index: int = -1,
                   num_classes: Optional[int] = None,
                   delimiter: str = ",") -> DataSet:
    feats, labels = [], []
    for line in lines:
        if not line.strip():
            continue
        vals = [float(p) for p in line.strip().split(delimiter)]
        li = label_index if label_index >= 0 else len(vals) - 1
        label = vals[li]
        feats.append([v for i, v in enumerate(vals) if i != li])
        if num_classes:
            oh = np.zeros(num_classes, np.float32)
            oh[int(label)] = 1.0
            labels.append(oh)
        else:
            labels.append([label])
    return DataSet(np.asarray(feats, np.float32),
                   np.asarray(labels, np.float32))
