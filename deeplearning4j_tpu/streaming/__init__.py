"""NDArray streaming (reference: dl4j-streaming — Kafka+Camel NDArray
pub/sub + CSV→DataSet conversion, `streaming/kafka/NDArrayKafkaClient.java`).

The transport is pluggable: `LocalQueueTransport` is the in-process
implementation (and the test double); `LocalLogTransport` is its
offset-addressable sibling (append-only retained log, `read(topic,
offset)` — the replay-from-offset primitive the online-training cursor
contract rides); `KafkaTransport` gates on the optional kafka-python
dependency, which is not bundled in this image — the wire format
(ndarray → bytes) is transport-independent.
"""

from deeplearning4j_tpu.streaming.ndarray import (
    KafkaTransport,
    LocalLogTransport,
    LocalQueueTransport,
    NDArrayConsumer,
    NDArrayPublisher,
    deserialize_ndarray,
    serialize_ndarray,
)
from deeplearning4j_tpu.streaming.records import csv_to_dataset
from deeplearning4j_tpu.streaming.routes import RecordPublishRoute, ServingRoute
