"""NDArray pub/sub.

Reference: `streaming/kafka/NDArrayKafkaClient.java` +
`NDArrayPublisher`/`NDArrayConsumer` (Camel routes). Wire format here:
little-endian header (magic, dtype code, ndim, dims) + raw buffer —
transport-independent, so the local queue and Kafka carry identical
bytes.
"""

from __future__ import annotations

import queue
import struct
from typing import Dict, Optional

import numpy as np

# bfloat16 rides jax's bundled ml_dtypes (no new dependency): serving
# activations and the mixed_bf16 training wire are bf16, and the
# request plane must carry them without a silent fp32 up-cast doubling
# every payload. int8 carries quantized serving payloads (nd/quant.py)
# for the same reason.
from ml_dtypes import bfloat16 as _bf16

_MAGIC = b"ND4T"
_DTYPES = {0: np.float32, 1: np.float64, 2: np.int32, 3: np.int64,
           4: _bf16, 5: np.int8}
_DTYPE_CODES = {np.dtype(v): k for k, v in _DTYPES.items()}


def serialize_ndarray(arr: np.ndarray) -> bytes:
    arr = np.ascontiguousarray(arr)
    code = _DTYPE_CODES.get(arr.dtype)
    if code is None:
        raise TypeError(
            f"Unsupported dtype {arr.dtype}; the ND4T wire carries "
            f"{sorted(str(np.dtype(d)) for d in _DTYPES.values())}")
    header = _MAGIC + struct.pack("<BB", code, arr.ndim)
    header += struct.pack(f"<{arr.ndim}q", *arr.shape)
    return header + arr.tobytes()


def deserialize_ndarray(data: bytes) -> np.ndarray:
    if data[:4] != _MAGIC:
        raise ValueError("Not an ND4T payload (bad magic)")
    code, ndim = struct.unpack_from("<BB", data, 4)
    if code not in _DTYPES:
        # name the offending code: a payload from a NEWER wire revision
        # must fail diagnosably, not as a KeyError deep in numpy
        raise ValueError(
            f"Unknown ND4T dtype code {code} (this reader knows codes "
            f"{sorted(_DTYPES)}); payload written by a newer wire "
            f"revision?")
    dims = struct.unpack_from(f"<{ndim}q", data, 6)
    off = 6 + 8 * ndim
    return np.frombuffer(data, _DTYPES[code], int(np.prod(dims)),
                         off).reshape(dims).copy()


class Transport:
    def send(self, topic: str, payload: bytes) -> None:
        raise NotImplementedError

    def receive(self, topic: str, timeout: Optional[float] = None) -> bytes:
        raise NotImplementedError

    def close(self, topic: str) -> None:
        """Release per-topic resources (consumers, buffers). The fleet
        request plane allocates ONE reply topic per request — without
        this hook a long-lived client leaks a queue (local) or an open
        consumer socket (Kafka) per finished request."""

    def __enter__(self):
        return self

    def __exit__(self, *a):
        return False


class LocalQueueTransport(Transport):
    """In-process transport (test double for the Kafka/Camel route)."""

    def __init__(self):
        self._queues: Dict[str, queue.Queue] = {}

    def _q(self, topic):
        return self._queues.setdefault(topic, queue.Queue())

    def send(self, topic, payload):
        self._q(topic).put(payload)

    def receive(self, topic, timeout=None):
        return self._q(topic).get(timeout=timeout)

    def close(self, topic):
        self._queues.pop(topic, None)


class LocalLogTransport(Transport):
    """In-process append-only log transport — the offset-addressable
    variant of `LocalQueueTransport` (a Kafka partition's semantics
    without the broker): `send` appends, messages are never destroyed
    by consumption, and `read(topic, offset)` addresses any retained
    message by position.

    This is what makes the online-training resume contract testable
    in-tree: a `StreamingDataSetIterator` cursor is a transport offset,
    and replay-from-offset after a crash means re-reading the SAME
    record sequence — impossible over a destructive queue. `receive()`
    stays Transport-compatible (one shared consumer cursor advancing
    through the log), so everything that runs over LocalQueueTransport
    runs over this unchanged.
    """

    def __init__(self):
        import threading
        self._logs: Dict[str, list] = {}
        self._cursors: Dict[str, int] = {}
        self._cond = threading.Condition()

    def send(self, topic, payload):
        with self._cond:
            self._logs.setdefault(topic, []).append(payload)
            self._cond.notify_all()

    def producer_offset(self, topic: str) -> int:
        """Messages appended so far — the head the consumer lag
        (`streaming_lag_records`) is measured against."""
        with self._cond:
            return len(self._logs.get(topic, ()))

    def read(self, topic: str, offset: int,
             timeout: Optional[float] = None) -> bytes:
        """Blocking offset-addressed read: the message at `offset`
        (0-based append order), waiting up to `timeout` for the
        producer to reach it. Raises TimeoutError like `receive`."""
        import time as _time
        deadline = None if timeout is None else _time.monotonic() + timeout
        with self._cond:
            while len(self._logs.get(topic, ())) <= offset:
                remaining = (None if deadline is None
                             else deadline - _time.monotonic())
                if remaining is not None and remaining <= 0:
                    raise TimeoutError(
                        f"no message at offset {offset} on {topic}")
                self._cond.wait(remaining if remaining is not None
                                else 1.0)
            return self._logs[topic][offset]

    def receive(self, topic, timeout=None):
        import time as _time
        deadline = None if timeout is None else _time.monotonic() + timeout
        with self._cond:
            # claim-under-lock: concurrent receivers each take a
            # distinct offset (queue semantics over the retained log)
            while len(self._logs.get(topic, ())) <= \
                    self._cursors.get(topic, 0):
                remaining = (None if deadline is None
                             else deadline - _time.monotonic())
                if remaining is not None and remaining <= 0:
                    raise TimeoutError(f"No message on {topic}")
                self._cond.wait(remaining if remaining is not None
                                else 1.0)
            off = self._cursors.get(topic, 0)
            self._cursors[topic] = off + 1
            return self._logs[topic][off]

    def close(self, topic):
        with self._cond:
            self._logs.pop(topic, None)
            self._cursors.pop(topic, None)


class KafkaTransport(Transport):
    """Kafka-backed transport; requires kafka-python (not bundled)."""

    def __init__(self, bootstrap_servers: str):
        try:
            from kafka import KafkaConsumer, KafkaProducer  # noqa: F401
        except ImportError as e:
            raise ImportError(
                "KafkaTransport needs the kafka-python package; install it "
                "or use LocalQueueTransport") from e
        from kafka import KafkaProducer
        self._producer = KafkaProducer(bootstrap_servers=bootstrap_servers)
        self._bootstrap = bootstrap_servers
        self._consumers: Dict[str, object] = {}

    def send(self, topic, payload):
        self._producer.send(topic, payload)
        self._producer.flush()

    def receive(self, topic, timeout=None):
        from kafka import KafkaConsumer
        if topic not in self._consumers:
            self._consumers[topic] = KafkaConsumer(
                topic, bootstrap_servers=self._bootstrap,
                auto_offset_reset="earliest")
        ms = int((timeout or 10) * 1000)
        batch = self._consumers[topic].poll(timeout_ms=ms, max_records=1)
        for records in batch.values():
            return records[0].value
        raise TimeoutError(f"No message on {topic}")

    def read(self, topic: str, offset: int,
             timeout: Optional[float] = None) -> bytes:
        """Offset-addressed read via a dedicated seeking consumer —
        the replay-from-offset primitive the online-training cursor
        contract needs (`StreamingDataSetIterator.seek`). Wired but
        NOT exercised in CI: the image ships no broker (see
        docs/STREAMING_TRAINING.md, honest limits)."""
        from kafka import KafkaConsumer, TopicPartition
        key = f"{topic}\x00seek"
        if key not in self._consumers:
            c = KafkaConsumer(bootstrap_servers=self._bootstrap)
            c.assign([TopicPartition(topic, 0)])
            self._consumers[key] = c
        c = self._consumers[key]
        c.seek(TopicPartition(topic, 0), int(offset))
        ms = int((timeout or 10) * 1000)
        batch = c.poll(timeout_ms=ms, max_records=1)
        for records in batch.values():
            return records[0].value
        raise TimeoutError(f"No message at offset {offset} on {topic}")

    def producer_offset(self, topic: str) -> int:
        """The partition's end offset (producer head) — the lag
        gauge's reference point."""
        from kafka import KafkaConsumer, TopicPartition
        c = KafkaConsumer(bootstrap_servers=self._bootstrap)
        try:
            tp = TopicPartition(topic, 0)
            return int(c.end_offsets([tp])[tp])
        finally:
            c.close()

    def close(self, topic):
        for key in (topic, f"{topic}\x00seek"):
            consumer = self._consumers.pop(key, None)
            if consumer is not None:
                consumer.close()


class NDArrayPublisher:
    def __init__(self, transport: Transport, topic: str):
        self.transport = transport
        self.topic = topic

    def publish(self, arr: np.ndarray):
        self.transport.send(self.topic, serialize_ndarray(arr))


class NDArrayConsumer:
    def __init__(self, transport: Transport, topic: str):
        self.transport = transport
        self.topic = topic

    def consume(self, timeout: Optional[float] = None) -> np.ndarray:
        return deserialize_ndarray(self.transport.receive(self.topic, timeout))
