"""NDArray pub/sub.

Reference: `streaming/kafka/NDArrayKafkaClient.java` +
`NDArrayPublisher`/`NDArrayConsumer` (Camel routes). Wire format here:
little-endian header (magic, dtype code, ndim, dims) + raw buffer —
transport-independent, so the local queue and Kafka carry identical
bytes.
"""

from __future__ import annotations

import queue
import struct
from typing import Dict, Optional

import numpy as np

_MAGIC = b"ND4T"
_DTYPES = {0: np.float32, 1: np.float64, 2: np.int32, 3: np.int64}
_DTYPE_CODES = {np.dtype(v): k for k, v in _DTYPES.items()}


def serialize_ndarray(arr: np.ndarray) -> bytes:
    arr = np.ascontiguousarray(arr)
    code = _DTYPE_CODES.get(arr.dtype)
    if code is None:
        raise TypeError(f"Unsupported dtype {arr.dtype}")
    header = _MAGIC + struct.pack("<BB", code, arr.ndim)
    header += struct.pack(f"<{arr.ndim}q", *arr.shape)
    return header + arr.tobytes()


def deserialize_ndarray(data: bytes) -> np.ndarray:
    if data[:4] != _MAGIC:
        raise ValueError("Not an ND4T payload (bad magic)")
    code, ndim = struct.unpack_from("<BB", data, 4)
    dims = struct.unpack_from(f"<{ndim}q", data, 6)
    off = 6 + 8 * ndim
    return np.frombuffer(data, _DTYPES[code], int(np.prod(dims)),
                         off).reshape(dims).copy()


class Transport:
    def send(self, topic: str, payload: bytes) -> None:
        raise NotImplementedError

    def receive(self, topic: str, timeout: Optional[float] = None) -> bytes:
        raise NotImplementedError


class LocalQueueTransport(Transport):
    """In-process transport (test double for the Kafka/Camel route)."""

    def __init__(self):
        self._queues: Dict[str, queue.Queue] = {}

    def _q(self, topic):
        return self._queues.setdefault(topic, queue.Queue())

    def send(self, topic, payload):
        self._q(topic).put(payload)

    def receive(self, topic, timeout=None):
        return self._q(topic).get(timeout=timeout)


class KafkaTransport(Transport):
    """Kafka-backed transport; requires kafka-python (not bundled)."""

    def __init__(self, bootstrap_servers: str):
        try:
            from kafka import KafkaConsumer, KafkaProducer  # noqa: F401
        except ImportError as e:
            raise ImportError(
                "KafkaTransport needs the kafka-python package; install it "
                "or use LocalQueueTransport") from e
        from kafka import KafkaProducer
        self._producer = KafkaProducer(bootstrap_servers=bootstrap_servers)
        self._bootstrap = bootstrap_servers
        self._consumers: Dict[str, object] = {}

    def send(self, topic, payload):
        self._producer.send(topic, payload)
        self._producer.flush()

    def receive(self, topic, timeout=None):
        from kafka import KafkaConsumer
        if topic not in self._consumers:
            self._consumers[topic] = KafkaConsumer(
                topic, bootstrap_servers=self._bootstrap,
                auto_offset_reset="earliest")
        ms = int((timeout or 10) * 1000)
        batch = self._consumers[topic].poll(timeout_ms=ms, max_records=1)
        for records in batch.values():
            return records[0].value
        raise TimeoutError(f"No message on {topic}")


class NDArrayPublisher:
    def __init__(self, transport: Transport, topic: str):
        self.transport = transport
        self.topic = topic

    def publish(self, arr: np.ndarray):
        self.transport.send(self.topic, serialize_ndarray(arr))


class NDArrayConsumer:
    def __init__(self, transport: Transport, topic: str):
        self.transport = transport
        self.topic = topic

    def consume(self, timeout: Optional[float] = None) -> np.ndarray:
        return deserialize_ndarray(self.transport.receive(self.topic, timeout))
