"""NDArray pub/sub.

Reference: `streaming/kafka/NDArrayKafkaClient.java` +
`NDArrayPublisher`/`NDArrayConsumer` (Camel routes). Wire format here:
little-endian header (magic, dtype code, ndim, dims) + raw buffer —
transport-independent, so the local queue and Kafka carry identical
bytes.
"""

from __future__ import annotations

import queue
import struct
from typing import Dict, Optional

import numpy as np

# bfloat16 rides jax's bundled ml_dtypes (no new dependency): serving
# activations and the mixed_bf16 training wire are bf16, and the
# request plane must carry them without a silent fp32 up-cast doubling
# every payload. int8 carries quantized serving payloads (nd/quant.py)
# for the same reason.
from ml_dtypes import bfloat16 as _bf16

_MAGIC = b"ND4T"
_DTYPES = {0: np.float32, 1: np.float64, 2: np.int32, 3: np.int64,
           4: _bf16, 5: np.int8}
_DTYPE_CODES = {np.dtype(v): k for k, v in _DTYPES.items()}


def serialize_ndarray(arr: np.ndarray) -> bytes:
    arr = np.ascontiguousarray(arr)
    code = _DTYPE_CODES.get(arr.dtype)
    if code is None:
        raise TypeError(
            f"Unsupported dtype {arr.dtype}; the ND4T wire carries "
            f"{sorted(str(np.dtype(d)) for d in _DTYPES.values())}")
    header = _MAGIC + struct.pack("<BB", code, arr.ndim)
    header += struct.pack(f"<{arr.ndim}q", *arr.shape)
    return header + arr.tobytes()


def deserialize_ndarray(data: bytes) -> np.ndarray:
    if data[:4] != _MAGIC:
        raise ValueError("Not an ND4T payload (bad magic)")
    code, ndim = struct.unpack_from("<BB", data, 4)
    if code not in _DTYPES:
        # name the offending code: a payload from a NEWER wire revision
        # must fail diagnosably, not as a KeyError deep in numpy
        raise ValueError(
            f"Unknown ND4T dtype code {code} (this reader knows codes "
            f"{sorted(_DTYPES)}); payload written by a newer wire "
            f"revision?")
    dims = struct.unpack_from(f"<{ndim}q", data, 6)
    off = 6 + 8 * ndim
    return np.frombuffer(data, _DTYPES[code], int(np.prod(dims)),
                         off).reshape(dims).copy()


class Transport:
    def send(self, topic: str, payload: bytes) -> None:
        raise NotImplementedError

    def receive(self, topic: str, timeout: Optional[float] = None) -> bytes:
        raise NotImplementedError

    def close(self, topic: str) -> None:
        """Release per-topic resources (consumers, buffers). The fleet
        request plane allocates ONE reply topic per request — without
        this hook a long-lived client leaks a queue (local) or an open
        consumer socket (Kafka) per finished request."""

    def __enter__(self):
        return self

    def __exit__(self, *a):
        return False


class LocalQueueTransport(Transport):
    """In-process transport (test double for the Kafka/Camel route)."""

    def __init__(self):
        self._queues: Dict[str, queue.Queue] = {}

    def _q(self, topic):
        return self._queues.setdefault(topic, queue.Queue())

    def send(self, topic, payload):
        self._q(topic).put(payload)

    def receive(self, topic, timeout=None):
        return self._q(topic).get(timeout=timeout)

    def close(self, topic):
        self._queues.pop(topic, None)


class KafkaTransport(Transport):
    """Kafka-backed transport; requires kafka-python (not bundled)."""

    def __init__(self, bootstrap_servers: str):
        try:
            from kafka import KafkaConsumer, KafkaProducer  # noqa: F401
        except ImportError as e:
            raise ImportError(
                "KafkaTransport needs the kafka-python package; install it "
                "or use LocalQueueTransport") from e
        from kafka import KafkaProducer
        self._producer = KafkaProducer(bootstrap_servers=bootstrap_servers)
        self._bootstrap = bootstrap_servers
        self._consumers: Dict[str, object] = {}

    def send(self, topic, payload):
        self._producer.send(topic, payload)
        self._producer.flush()

    def receive(self, topic, timeout=None):
        from kafka import KafkaConsumer
        if topic not in self._consumers:
            self._consumers[topic] = KafkaConsumer(
                topic, bootstrap_servers=self._bootstrap,
                auto_offset_reset="earliest")
        ms = int((timeout or 10) * 1000)
        batch = self._consumers[topic].poll(timeout_ms=ms, max_records=1)
        for records in batch.values():
            return records[0].value
        raise TimeoutError(f"No message on {topic}")

    def close(self, topic):
        consumer = self._consumers.pop(topic, None)
        if consumer is not None:
            consumer.close()


class NDArrayPublisher:
    def __init__(self, transport: Transport, topic: str):
        self.transport = transport
        self.topic = topic

    def publish(self, arr: np.ndarray):
        self.transport.send(self.topic, serialize_ndarray(arr))


class NDArrayConsumer:
    def __init__(self, transport: Transport, topic: str):
        self.transport = transport
        self.topic = topic

    def consume(self, timeout: Optional[float] = None) -> np.ndarray:
        return deserialize_ndarray(self.transport.receive(self.topic, timeout))
