"""Streaming serve/publish routes.

Reference: `dl4j-streaming/.../routes/DL4jServeRouteBuilder.java` (a
Camel route: consume serialized NDArrays from a Kafka topic → optional
pre-processor → restore model → `output()` → optional final processor →
publish to the output URI) and `CamelKafkaRouteBuilder.java` (records →
serialized arrays → topic). Camel's role — wiring transports to
processors — is plain composition here over the same `Transport`
abstraction (`streaming/ndarray.py`: LocalQueue or Kafka), so the
routes run identically on the in-memory transport in tests and on a
real broker in production.
"""

from __future__ import annotations

import threading
from typing import Callable, Iterable, Optional

import numpy as np

from deeplearning4j_tpu.streaming.ndarray import (
    NDArrayConsumer,
    NDArrayPublisher,
    Transport,
)


class ServingRoute:
    """consume(topic) → before → route through the fleet router →
    final → publish(topic).

    `model`: anything with `.output(x)` (MultiLayerNetwork or
    ComputationGraph — pass `model_uri` instead to lazy-restore from a
    checkpoint zip, the reference's `modelUri` mode).

    The forward itself goes through a `FleetRouter` output backend
    (`serving/router.py`) — the route is a transport adapter over the
    same front end the generation fleet uses, so a plain forward-
    serving route shares the router's per-model request accounting
    (`fleet_output_requests_total{model=}`), its `max_queue` shed
    backstop, and (when `router=` is a shared instance) a single
    admission plane with the generation models. By default each route
    owns a private single-model router named `model_name`."""

    def __init__(self, transport: Transport, consuming_topic: str,
                 output_topic: str, model=None, model_uri: Optional[str] = None,
                 before: Optional[Callable[[np.ndarray], np.ndarray]] = None,
                 final: Optional[Callable[[np.ndarray], np.ndarray]] = None,
                 router=None, model_name: Optional[str] = None,
                 max_queue: Optional[int] = None):
        if model is None and model_uri is None:
            raise ValueError("need model or model_uri")
        self.transport = transport
        self.consuming_topic = consuming_topic
        self.output_topic = output_topic
        self._model = model
        self.model_uri = model_uri
        self.before = before
        self.final = final
        self.model_name = model_name or f"route:{consuming_topic}"
        if router is None:
            from deeplearning4j_tpu.serving.router import FleetRouter
            router = FleetRouter(max_queue=max_queue)
        self.router = router
        self._attached = False
        self._consumer = NDArrayConsumer(transport, consuming_topic)
        self._publisher = NDArrayPublisher(transport, output_topic)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    @property
    def model(self):
        if self._model is None:
            from deeplearning4j_tpu.util.serializer import ModelSerializer
            self._model = ModelSerializer.restore_model(self.model_uri)
        return self._model

    def _router_backend(self):
        """Attach the (possibly lazily-restored) model to the router as
        an output backend exactly once."""
        if not self._attached:
            self.router.attach_output(self.model_name, self.model)
            self._attached = True
        return self.router

    # ---------------------------------------------------------- processing
    def process_one(self, timeout: Optional[float] = None) -> bool:
        """One exchange through the route; False on consume timeout.
        Transport/deserialization errors propagate — an empty topic and
        a broken broker must not look the same."""
        import queue as _queue
        try:
            x = self._consumer.consume(timeout=timeout)
        except (TimeoutError, _queue.Empty):
            return False
        if x is None:
            return False
        if self.before is not None:
            x = self.before(x)
        out = self._router_backend().route_output(self.model_name, x)
        if self.final is not None:
            out = self.final(out)
        self._publisher.publish(np.asarray(out))
        return True

    def run(self, max_messages: Optional[int] = None,
            timeout: Optional[float] = 1.0) -> int:
        """Drain the topic (until timeout or max_messages). Returns the
        number of messages served."""
        served = 0
        while max_messages is None or served < max_messages:
            if self._stop.is_set() or not self.process_one(timeout=timeout):
                break
            served += 1
        return served

    # ------------------------------------------------------- background run
    def start(self, poll_timeout: float = 0.2):
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._loop, args=(poll_timeout,), daemon=True)
        self._thread.start()
        return self

    def _loop(self, poll_timeout):
        import logging
        while not self._stop.is_set():
            try:
                self.process_one(timeout=poll_timeout)
            except Exception:
                # background serving must survive transient broker
                # errors; log and keep polling (reference Camel route
                # error-handler role)
                logging.getLogger(__name__).exception(
                    "serving route error (continuing)")
                self._stop.wait(poll_timeout)

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None


class RecordPublishRoute:
    """records → feature arrays → topic (reference
    `CamelKafkaRouteBuilder` record-serialize-publish leg)."""

    def __init__(self, transport: Transport, topic: str,
                 extractor: Optional[Callable] = None):
        self.publisher = NDArrayPublisher(transport, topic)
        self.extractor = extractor or (lambda r: np.asarray(r, np.float32))

    def publish(self, records: Iterable) -> int:
        n = 0
        for rec in records:
            self.publisher.publish(np.asarray(self.extractor(rec)))
            n += 1
        return n
