"""Word2Vec over raw (unspaced) Chinese text via the CJK tokenizer
seam (reference role: deeplearning4j-nlp-chinese's ansj
TokenizerFactory). The dictionary-DP segmenter turns character runs
into words; Word2Vec then trains exactly as it does for English."""
import numpy as np

from deeplearning4j_tpu.nlp.cjk import CJKTokenizerFactory
from deeplearning4j_tpu.nlp.word2vec import Word2Vec

LEXICON = {
    "猫": 50, "狗": 50, "鱼": 40, "肉": 40, "吃": 60, "喜欢": 60,
    "宠物": 30, "可爱": 25, "公园": 20, "玩": 25,
    "银行": 40, "股票": 40, "市场": 40, "价格": 30, "投资": 25,
    "上涨": 20, "我": 80, "的": 100, "在": 60, "和": 60, "了": 60,
}

CORPUS = [
    "我的猫喜欢吃鱼", "狗在公园玩", "我喜欢我的狗", "宠物猫吃鱼和肉",
    "可爱的猫在玩", "狗喜欢吃肉",
    "股票价格上涨了", "投资股票的价格", "银行投资市场", "价格在市场上涨",
] * 8


def main():
    w2v = Word2Vec(sentence_iterator=CORPUS,
                   tokenizer_factory=CJKTokenizerFactory(LEXICON),
                   layer_size=24, window_size=3, min_word_frequency=2,
                   negative_sample=5, epochs=4, seed=7)
    w2v.fit()
    print("nearest to 猫:", w2v.words_nearest("猫", top_n=4))
    print("nearest to 股票:", w2v.words_nearest("股票", top_n=4))
    print("sim(猫,狗) =", round(w2v.similarity("猫", "狗"), 3),
          " sim(猫,股票) =", round(w2v.similarity("猫", "股票"), 3))


if __name__ == "__main__":
    main()
