"""Data-parallel training over a device mesh with round stats + HTML
timeline (ParallelWrapper / TrainingMaster example role). Runs on
whatever devices exist — set XLA_FLAGS=--xla_force_host_platform_device_count=8
to simulate a mesh on CPU."""
import numpy as np
import jax
from jax.sharding import Mesh

from deeplearning4j_tpu.common.updaters import Adam
from deeplearning4j_tpu.datasets.fetchers import load_iris
from deeplearning4j_tpu.nn.conf import InputType, NeuralNetConfiguration
from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.parallel import ParameterAveragingTrainingMaster


def main():
    x, y = load_iris()
    conf = (NeuralNetConfiguration.builder().seed(42).updater(Adam(0.02))
            .list()
            .layer(DenseLayer(n_out=16, activation="relu"))
            .layer(OutputLayer(n_out=3, activation="softmax", loss="mcxent"))
            .set_input_type(InputType.feed_forward(4))
            .build())
    net = MultiLayerNetwork(conf).init()

    devices = jax.devices()
    mesh = Mesh(np.array(devices), ("data",))
    master = ParameterAveragingTrainingMaster(
        batch_size_per_worker=max(1, 64 // len(devices)),
        averaging_frequency=2, mesh=mesh, collect_training_stats=True)
    master.execute_training(net, (x, y), epochs=30)
    stats = master.get_training_stats()
    print("phase totals (ms):", stats.phase_totals_ms())
    print("timeline ->", stats.export_html("/tmp/training_timeline.html"))


if __name__ == "__main__":
    main()
