"""Object detection end-to-end: train a tiny YOLOv2 head on synthetic
boxes, then extract detections with get_predicted_objects + NMS.

Mirrors the reference's ObjectDetection examples
(Yolo2OutputLayer.java train path + :610-670 inference extraction).
Synthetic data: one bright square per image; the network learns to put
a confident box on it.
"""

import os

import numpy as np


def make_data(n, grid=6, cell_px=8, seed=0):
    """Images [n, 48, 48, 1] with one bright square; labels
    [n, grid, grid, 4+C] in grid units (C=1 class)."""
    rng = np.random.default_rng(seed)
    H = grid * cell_px
    x = rng.normal(0.0, 0.1, (n, H, H, 1)).astype(np.float32)
    y = np.zeros((n, grid, grid, 5), np.float32)
    for i in range(n):
        gx, gy = rng.integers(1, grid - 1, 2)
        cx, cy = gx + 0.5, gy + 0.5      # box center, grid units
        w = h = 1.6
        px, py = int(cx * cell_px), int(cy * cell_px)
        half = int(w * cell_px / 2)
        x[i, py - half:py + half, px - half:px + half, 0] += 1.0
        cell = y[i, gy, gx]
        cell[0:4] = [cx - w / 2, cy - h / 2, cx + w / 2, cy + h / 2]
        cell[4] = 1.0                     # one-hot class 0
    return x, y


def main():
    if os.environ.get("DL4J_FORCE_CPU"):
        # sandbox escape hatch: the axon TPU plugin hangs on a dead
        # tunnel; `DL4J_FORCE_CPU=1 python examples/object_detection.py`
        # pins the CPU backend before any jax backend use
        import jax
        jax.config.update("jax_platforms", "cpu")
    from deeplearning4j_tpu.common.updaters import Adam
    from deeplearning4j_tpu.nn.conf import InputType, NeuralNetConfiguration
    from deeplearning4j_tpu.nn.layers import ConvolutionLayer, SubsamplingLayer
    from deeplearning4j_tpu.nn.layers.objdetect import (
        Yolo2OutputLayer, non_max_suppression)
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

    grid, cell_px = 6, 8
    anchors = ((1.5, 1.5),)
    conf = (NeuralNetConfiguration.builder().seed(7).updater(Adam(5e-3))
            .list()
            .layer(ConvolutionLayer(n_out=16, kernel_size=(3, 3),
                                    activation="relu",
                                    convolution_mode="same"))
            .layer(SubsamplingLayer(kernel_size=(2, 2), stride=(2, 2)))
            .layer(ConvolutionLayer(n_out=32, kernel_size=(3, 3),
                                    activation="relu",
                                    convolution_mode="same"))
            .layer(SubsamplingLayer(kernel_size=(2, 2), stride=(2, 2)))
            .layer(SubsamplingLayer(kernel_size=(2, 2), stride=(2, 2)))
            .layer(ConvolutionLayer(n_out=len(anchors) * 6,
                                    kernel_size=(1, 1),
                                    activation="identity"))
            .layer(Yolo2OutputLayer(anchors=anchors))
            .set_input_type(InputType.convolutional(grid * cell_px,
                                                    grid * cell_px, 1))
            .build())
    net = MultiLayerNetwork(conf).init()

    x, y = make_data(64, grid, cell_px)
    print("training 120 epochs on 64 synthetic images ...")
    net.fit(x, y, epochs=120, batch_size=32)
    print(f"final loss {net.score_value:.4f}")

    # inference: activated output → thresholded boxes → NMS
    yolo = net.layers[-1]
    xt, yt = make_data(4, grid, cell_px, seed=99)
    out = net.output(xt)
    dets = non_max_suppression(
        # confidence trains toward the predicted box's IOU, so a
        # well-fit box sits at ~0.5-0.8 confidence — threshold below it
        yolo.get_predicted_objects(out, threshold=0.35), iou_threshold=0.4)
    for d in dets:
        tlx, tly = d.top_left_xy
        brx, bry = d.bottom_right_xy
        # grid units → pixels (the reference's doc example: x32 there)
        print(f"example {d.example_number}: class {d.predicted_class} "
              f"conf {d.confidence:.2f} box px "
              f"({tlx * cell_px:.0f},{tly * cell_px:.0f})-"
              f"({brx * cell_px:.0f},{bry * cell_px:.0f})")
    found = {d.example_number for d in dets}
    print(f"detected objects in {len(found)}/4 held-out images")


if __name__ == "__main__":
    main()
