"""Transfer learning + checkpoint + streaming serving: fine-tune a
feature extractor, save, serve over an in-process route (dl4j-examples
TransferLearning + streaming role)."""
import numpy as np

from deeplearning4j_tpu.common.updaters import Adam
from deeplearning4j_tpu.datasets.fetchers import load_iris
from deeplearning4j_tpu.nn.conf import InputType, NeuralNetConfiguration
from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.streaming import (
    LocalQueueTransport, NDArrayConsumer, NDArrayPublisher, ServingRoute)
from deeplearning4j_tpu.transferlearning import TransferLearning
from deeplearning4j_tpu.util import ModelSerializer


def main():
    x, y = load_iris()
    conf = (NeuralNetConfiguration.builder().seed(1).updater(Adam(0.02))
            .list()
            .layer(DenseLayer(n_out=32, activation="relu"))
            .layer(DenseLayer(n_out=16, activation="relu"))
            .layer(OutputLayer(n_out=3, activation="softmax", loss="mcxent"))
            .set_input_type(InputType.feed_forward(4))
            .build())
    base = MultiLayerNetwork(conf).init()
    base.fit(x, y, epochs=40, batch_size=50)

    # freeze the trunk, replace the head, fine-tune
    tuned = (TransferLearning.Builder(base)
             .set_feature_extractor(1)
             .n_out_replace(2, 3)
             .build())
    tuned.fit(x, y, epochs=10, batch_size=50)

    ModelSerializer.write_model(tuned, "/tmp/iris_model.zip")

    transport = LocalQueueTransport()
    route = ServingRoute(transport, "in", "out",
                         model_uri="/tmp/iris_model.zip")
    NDArrayPublisher(transport, "in").publish(x[:5])
    route.run(max_messages=1, timeout=0.5)
    print("served:", np.asarray(
        NDArrayConsumer(transport, "out").consume(timeout=1.0)).argmax(1))


if __name__ == "__main__":
    main()
