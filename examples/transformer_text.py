"""Transformer text classifier (beyond-reference long-context model):
detect whether a keyword token appears anywhere in the sequence."""
import numpy as np

from deeplearning4j_tpu.nn.layers.pooling import PoolingType
from deeplearning4j_tpu.zoo import TransformerClassifier


def main():
    rng = np.random.default_rng(0)
    n, T, V = 512, 24, 40
    ids = rng.integers(1, V, (n, T))
    labels = rng.random(n) < 0.5
    for i in np.nonzero(labels)[0]:
        ids[i, rng.integers(0, T)] = 0           # plant the keyword
    y = np.eye(2, dtype=np.float32)[labels.astype(int)]

    net = TransformerClassifier(vocab_size=V, num_classes=2, d_model=48,
                                n_layers=2, n_heads=4,
                                pooling=PoolingType.MAX, seed=7).init()
    net.fit(ids.astype(np.float32), y, epochs=15, batch_size=64,
            steps_per_execution=4)
    pred = np.asarray(net.output(ids.astype(np.float32))).argmax(1)
    print("train accuracy:", (pred == labels.astype(int)).mean())


if __name__ == "__main__":
    main()
