"""Character-level LSTM text generation (GravesLSTM example role):
train on a tiny corpus, then sample with rnn_time_step streaming."""
import numpy as np

from deeplearning4j_tpu.zoo.textgenlstm import TextGenerationLSTM

CORPUS = ("the quick brown fox jumps over the lazy dog. "
          "pack my box with five dozen liquor jugs. ") * 40


def main():
    chars = sorted(set(CORPUS))
    idx = {c: i for i, c in enumerate(chars)}
    V, T = len(chars), 40
    ids = np.array([idx[c] for c in CORPUS])
    starts = np.arange(0, len(ids) - T - 1, T // 2)
    x = np.eye(V, dtype=np.float32)[np.stack([ids[s:s + T] for s in starts])]
    y = np.eye(V, dtype=np.float32)[np.stack([ids[s + 1:s + T + 1] for s in starts])]

    net = TextGenerationLSTM(vocab_size=V, hidden=128).init()
    net.fit(x, y, epochs=20, batch_size=32, steps_per_execution=4)

    # streaming sampling
    net.rnn_clear_previous_state()
    rng = np.random.default_rng(0)
    cur = idx["t"]
    out = ["t"]
    for _ in range(120):
        probs = np.asarray(net.rnn_time_step(
            np.eye(V, dtype=np.float32)[[cur]]))[0]
        cur = int(rng.choice(V, p=probs / probs.sum()))
        out.append(chars[cur])
    print("".join(out))


if __name__ == "__main__":
    main()
