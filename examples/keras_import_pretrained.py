"""Keras model import + packaged pretrained zoo weights.

Two migration paths a DL4J user relies on (reference:
`KerasModelImport.java`, `ZooModel.initPretrained`):

1. import a Keras .h5 (any of the Keras 1/2/3 dialects) — a COMPILED
   model keeps its loss/optimizer and can keep training here;
2. load a zoo model's pretrained checkpoint and use/fine-tune it.
"""
from pathlib import Path

import numpy as np

from deeplearning4j_tpu.eval import Evaluation
from deeplearning4j_tpu.modelimport.keras import KerasModelImport
from deeplearning4j_tpu.zoo.base import PretrainedType
from deeplearning4j_tpu.zoo.lenet import LeNet

FIXTURES = Path(__file__).parents[1] / "tests" / "fixtures" / "keras"


def import_and_finetune():
    # real_bn.h5 was saved by genuine Keras after model.compile(...):
    # the import maps its loss + optimizer, so fit() works immediately
    net = KerasModelImport.import_keras_model_and_weights(
        str(FIXTURES / "real_bn.h5"))
    rng = np.random.default_rng(0)
    x = rng.standard_normal((32, 6, 6, 2)).astype(np.float32)
    y = np.eye(3, dtype=np.float32)[rng.integers(0, 3, 32)]
    print("imported output:", np.asarray(net.output(x[:2])).round(3))
    net.fit(x, y, epochs=3)
    print("fine-tuned score:", net.score_value)


def pretrained_zoo():
    # ships inside the package (zoo/weights/); trained on the real
    # sklearn handwritten-digits corpus — no network needed
    net = LeNet().init_pretrained(PretrainedType.MNIST)
    from sklearn.datasets import load_digits
    import jax
    import jax.numpy as jnp

    d = load_digits()
    x = d.images.astype(np.float32) / 16.0
    x = np.asarray(jax.image.resize(jnp.asarray(x), (len(x), 28, 28),
                                    "bilinear"))[..., None]
    y = np.eye(10, dtype=np.float32)[d.target]
    ev = Evaluation(10)
    ev.eval(y[:300], np.asarray(net.output(x[:300])))
    print(ev.stats(include_per_class=False))


if __name__ == "__main__":
    import_and_finetune()
    pretrained_zoo()
