"""Word2Vec skip-gram with stopword filtering and nearest-word queries
(reference Word2VecRawTextExample role)."""
from deeplearning4j_tpu.nlp import StopWordsRemover, Word2Vec
from deeplearning4j_tpu.nlp.tokenization import DefaultTokenizerFactory

SENTENCES = [
    "the king rules the kingdom",
    "the queen rules the kingdom",
    "a dog chases the cat",
    "a cat chases the mouse",
    "the king and the queen sit on thrones",
    "dogs and cats are animals",
] * 50


def main():
    tf = DefaultTokenizerFactory()
    tf.set_token_pre_processor(StopWordsRemover())
    w2v = Word2Vec(sentence_iterator=SENTENCES, tokenizer_factory=tf,
                   layer_size=32, window_size=3, negative_sample=5,
                   epochs=5, min_word_frequency=2, seed=1)
    w2v.fit()
    print("king ~", w2v.words_nearest("king", 3))
    print("sim(king, queen) =", round(w2v.similarity("king", "queen"), 3))
    print("sim(king, mouse) =", round(w2v.similarity("king", "mouse"), 3))


if __name__ == "__main__":
    main()
