"""LeNet on MNIST (falls back to synthetic digits offline) — the
classic first example: build, fit with listeners, evaluate."""
import numpy as np

from deeplearning4j_tpu.datasets.fetchers import MnistDataSetIterator
from deeplearning4j_tpu.eval import Evaluation
from deeplearning4j_tpu.optimize import PerformanceListener, ScoreIterationListener
from deeplearning4j_tpu.zoo.lenet import LeNet


def main():
    train = MnistDataSetIterator(batch_size=128, train=True, num_examples=6000,
                                 flatten=False)
    test = MnistDataSetIterator(batch_size=256, train=False, num_examples=1000,
                                flatten=False)
    net = LeNet(num_classes=10).init()
    net.set_listeners(ScoreIterationListener(10), PerformanceListener(10))
    # steps_per_execution fuses minibatch steps into one device dispatch
    net.fit(train, epochs=2, steps_per_execution=8)
    e: Evaluation = net.evaluate(test)
    print(e.stats())


if __name__ == "__main__":
    main()
