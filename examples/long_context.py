"""Long-context training recipe: sequence parallelism + remat.

The levers for sequences that don't fit one chip's HBM:
1. `sequence_parallel="ring"` (or "ulysses") on the transformer blocks —
   the time axis shards over a mesh "seq" axis; K/V blocks rotate over
   ICI (ring) or heads redistribute via all-to-all (Ulysses).
2. `remat=True` — intra-block activations are recomputed in backward
   instead of stored (one extra forward of FLOPs, big memory cut).
3. On TPU the SP schedules automatically ride the Pallas flash kernels
   in BOTH directions (`use_flash` auto) — the per-shard [Tl, Tl]
   attention tile never materializes, so the per-device memory is
   O(block), compounding with the sharding. Single chip, flash alone
   trains to T=65k where plain XLA attention OOMs at 16k.
4. The mesh rides the `sequence_sharding` context; the config carries
   only the strategy name, so checkpoints stay portable.

Runs on anything: 8 virtual CPU devices here, a real TPU pod slice in
production (same code, bigger mesh).
"""
import os

if not os.environ.get("DL4TPU_REAL_DEVICES"):
    # self-contained CPU demo: give the process 8 virtual devices
    # (must happen before jax initializes its backend). Set
    # DL4TPU_REAL_DEVICES=1 to run on the machine's real accelerators.
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                               + " --xla_force_host_platform_device_count=8")

import jax

if not os.environ.get("DL4TPU_REAL_DEVICES"):
    # in-process override beats plugin sitecustomize platform forcing
    jax.config.update("jax_platforms", "cpu")
import numpy as np

from deeplearning4j_tpu.parallel import MeshSpec, make_mesh, sequence_sharding
from deeplearning4j_tpu.zoo import TransformerLM


def main():
    rng = np.random.default_rng(0)
    n_seq = len(jax.devices())               # mesh sized to what exists
    V, B, T = 64, 4, 32 * n_seq              # T shards n_seq-ways
    ids = rng.integers(0, V, (B, T))
    x = ids.astype(np.float32)
    y = np.eye(V, dtype=np.float32)[(ids + 1) % V]   # next-token targets

    lm = TransformerLM(vocab_size=V, d_model=32, n_layers=2, n_heads=8,
                       max_len=T, sequence_parallel="ring", remat=True)
    net = lm.init()

    mesh = make_mesh(MeshSpec.of(seq=n_seq))
    with sequence_sharding(mesh, axis="seq"):
        net.fit(x, y, epochs=3, batch_size=B, shuffle=False)
    print("loss after 3 epochs:", round(net.score_value, 4))

    # inference outside the context falls back to the local path —
    # same numerics, no mesh needed
    out = np.asarray(net.output(x))
    print("output shape:", out.shape)


if __name__ == "__main__":
    main()
