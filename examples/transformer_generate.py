"""Autoregressive text generation with KV caches (transformer
counterpart of the char-LSTM `rnn_time_step` sampling): train a small
word-level LM on this repo's docs, then decode with
`zoo.transformer.generate` — the whole sampling loop is ONE fused
device dispatch (sampling happens on-device, rng carried)."""
import os
import re
from pathlib import Path

import numpy as np

from deeplearning4j_tpu.zoo.transformer import TransformerLM, generate


def load_tokens():
    repo = Path(__file__).parents[1]
    text = "\n".join(p.read_text(errors="ignore")
                     for p in [repo / "README.md",
                               *sorted((repo / "docs").glob("*.md"))])
    return re.findall(r"[a-z][a-z0-9_]+", text.lower())


def main():
    toks = load_tokens()
    vocab = sorted(set(toks))
    V, T = len(vocab), 32
    idx = {w: i for i, w in enumerate(vocab)}
    ids = np.array([idx[w] for w in toks], np.int32)
    n = (len(ids) - 1) // T
    x = ids[:n * T].reshape(n, T).astype(np.float32)
    y = np.eye(V, dtype=np.float32)[ids[1:n * T + 1].reshape(n, T)]

    net = TransformerLM(vocab_size=V, d_model=64, n_layers=2, n_heads=4,
                        max_len=64, seed=5).init()
    net.fit(x, y, epochs=3, batch_size=32, steps_per_execution=4)
    print("loss:", net.score_value)

    prompt_words = ["the", "reference"]
    prompt = np.array([[idx[w] for w in prompt_words]])
    out = generate(net, prompt, 24, temperature=0.8, top_p=0.9,
                   rng=__import__("jax").random.PRNGKey(0))
    print("sampled:", " ".join(prompt_words)
          + " " + " ".join(vocab[i] for i in out[0]))

    from deeplearning4j_tpu.zoo.transformer import beam_search
    ids, scores = beam_search(net, prompt, 12, beam_width=4)
    print("best beam (%.2f):" % scores[0, 0], " ".join(prompt_words)
          + " " + " ".join(vocab[i] for i in ids[0, 0]))


if __name__ == "__main__":
    main()
