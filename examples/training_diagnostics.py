"""Training-health diagnostics end to end: in-graph per-layer stats,
the non-finite watchdog, and the live training UI.

Run:  JAX_PLATFORMS=cpu python examples/training_diagnostics.py

What it shows:
- a model built with ``.diagnostics("skip")``: the fused train step
  emits per-layer gradient/update/param/activation statistics as aux
  outputs (zero extra syncs off-cadence, one batched transfer per
  report), and the watchdog discards non-finite updates in-graph;
- a deliberate learning-rate spike mid-run that would silently destroy
  the model — the ``skip`` policy rides through it and the counters
  record it;
- the stats flowing through StatsListener into the training UI
  (`/train/overview` training-health strip) and the Prometheus
  `/metrics` route (``training_*`` / ``watchdog_*`` families).
"""

import numpy as np

from deeplearning4j_tpu import monitor
from deeplearning4j_tpu.common.schedules import MapSchedule
from deeplearning4j_tpu.common.updaters import Sgd
from deeplearning4j_tpu.nn.conf import InputType, NeuralNetConfiguration
from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.ui import UIServer
from deeplearning4j_tpu.ui.stats import StatsListener
from deeplearning4j_tpu.ui.storage import InMemoryStatsStorage


def main():
    monitor.enable()

    # lr spikes to inf at iteration 10 — a classic silent-failure
    # injection (instability, bad batch, overflowing schedule): the
    # update goes non-finite, the watchdog discards it in-graph, and
    # training continues from the pre-spike params
    lr = MapSchedule({0: 0.05, 10: float("inf"), 11: 0.05})
    lb = (NeuralNetConfiguration.builder().seed(7).updater(Sgd(lr)).list())
    for _ in range(4):
        lb = lb.layer(DenseLayer(n_in=16, n_out=16, activation="tanh"))
    conf = (lb.layer(OutputLayer(n_in=16, n_out=4, activation="softmax",
                                 loss="mcxent"))
            .set_input_type(InputType.feed_forward(16))
            .diagnostics("skip")   # stats + watchdog: discard bad updates
            .build())
    net = MultiLayerNetwork(conf).init()

    storage = InMemoryStatsStorage()
    net.set_listeners(StatsListener(storage, collect_histograms=False))

    rng = np.random.default_rng(0)
    x = rng.standard_normal((640, 16)).astype(np.float32)
    w = rng.standard_normal((16, 4))
    y = np.eye(4, dtype=np.float32)[np.argmax(x @ w, axis=1)]
    net.fit(x, y, epochs=2, batch_size=32, shuffle=False)

    d = net._last_diagnostics
    print("\nlatest per-layer internals (from the fused step's aux):")
    for key in sorted(d["params"]):
        st = d["params"][key]
        print(f"  {key:6s} |g|={st['grad_mm']:.3e} |Δ|={st['upd_mm']:.3e} "
              f"ratio={st['ratio']:.3e}")
    for lk in sorted(d["activations"]):
        st = d["activations"][lk]
        print(f"  act {lk}: mean={st['mean']:+.3f} std={st['std']:.3f} "
              f"dead={st['dead']:.2f}")
    print(f"watchdog: nonfinite={net._diag.nonfinite_total} "
          f"skipped={net._diag.skipped_total} (the lr spike)")

    server = UIServer().start()
    server.attach(storage)
    print(f"\ntraining UI: http://127.0.0.1:{server.port}/train/overview "
          f"(training-health strip; ?lang=ja / ?lang=zh)")
    print(f"metrics:     http://127.0.0.1:{server.port}/metrics "
          f"(training_* / watchdog_* families)")
    server.stop()


if __name__ == "__main__":
    main()
