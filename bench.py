"""Headline benchmark: ResNet-50 training throughput (images/sec/chip).

BASELINE config 1 (north star). The reference publishes no numbers
(BASELINE.md); `REF_BASELINE` below is the comparison anchor we adopt:
a strong fp32 ResNet-50 per-V100 training throughput (~360 img/s) for
the DL4J-era cuDNN path the north star names. `vs_baseline` =
measured / REF_BASELINE.

Runs on whatever jax.default_backend() provides (the driver runs it on
one real TPU chip). Synthetic data (BenchmarkDataSetIterator pattern,
reference `datasets/iterator/impl/BenchmarkDataSetIterator.java`) so
ETL is excluded, matching how the reference's PerformanceListener
isolates compute.
"""

import json
import time

import jax
import jax.numpy as jnp
import numpy as np

REF_BASELINE = 360.0  # img/s — est. per-V100 fp32 ResNet-50 (cuDNN-era)


def main():
    from deeplearning4j_tpu.zoo.resnet50 import ResNet50

    on_tpu = jax.default_backend() == "tpu"
    batch = 64 if on_tpu else 8
    size = 224 if on_tpu else 64
    steps = 20 if on_tpu else 3

    model = ResNet50(num_classes=1000, height=size, width=size, channels=3)
    if on_tpu:
        # fp32 params, bf16 compute — convs hit the MXU at full rate
        from deeplearning4j_tpu.nd.dtype import bf16_policy
        from deeplearning4j_tpu.nn.graph import ComputationGraph
        net = ComputationGraph(model.conf(), dtype_policy=bf16_policy()).init(model.seed)
    else:
        net = model.init()

    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.standard_normal((batch, size, size, 3)), jnp.bfloat16 if on_tpu else jnp.float32)
    y = jnp.asarray(np.eye(1000, dtype=np.float32)[rng.integers(0, 1000, batch)])

    step = net._make_train_step()
    params, upd, state = net.params, net.updater_state, net.net_state

    # warmup / compile
    params, upd, state, loss = _run(step, params, upd, state, 0, x, y)
    jax.block_until_ready(loss)

    t0 = time.perf_counter()
    for i in range(1, steps + 1):
        params, upd, state, loss = _run(step, params, upd, state, i, x, y)
    jax.block_until_ready(loss)
    dt = time.perf_counter() - t0

    ips = batch * steps / dt
    print(json.dumps({
        "metric": "resnet50_train_images_per_sec_per_chip",
        "value": round(ips, 2),
        "unit": "images/sec",
        "vs_baseline": round(ips / REF_BASELINE, 3),
    }))


def _run(step, params, upd, state, it, x, y):
    out = step(params, upd, state, it, [x], [y], jax.random.PRNGKey(it), None, None)
    params, upd, state, loss = out[0], out[1], out[2], out[3]
    return params, upd, state, loss


if __name__ == "__main__":
    main()
