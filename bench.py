"""Driver entry point — the benchmark implementation lives in the
package (`deeplearning4j_tpu/bench.py`, also exposed as the
`dl4j-tpu-bench` console script) so it ships with the wheel; this shim
keeps the repo-root `python bench.py` contract."""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from deeplearning4j_tpu.bench import main  # noqa: E402

if __name__ == "__main__":
    main()
