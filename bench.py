"""Benchmarks for the driver (BASELINE.md configs).

Primary metric (BASELINE config 1, the north star): ResNet-50 training
throughput in images/sec/chip, with the accounting that makes the number
defensible:

- accelerator detection by `jax.devices()[0].platform` (any non-cpu
  platform — tpu, or the driver's tunneled 'axon' platform — runs the
  full 224x224 bf16-compute config);
- FLOPs/step both analytic (conv/fc MAC count) and from the compiled
  HLO (`.lower().compile().cost_analysis()`), giving achieved TFLOP/s
  and MFU against the chip's bf16 peak — a throughput claim implying
  MFU > 100% is reported as suspect (`mfu_plausible: false`);
- a train-signal check: the loss over the timed window must end lower
  than it started (same batch each step → the net must memorize).

Secondary metrics in `extras`: LeNet-MNIST epoch time (config 0),
GravesLSTM char-RNN throughput (config 2), Word2Vec skip-gram words/sec
(config 3), and multi-device data-parallel scaling efficiency on an
8-virtual-device CPU mesh (config 4 — scaling *shape*; run in a
subprocess so the accelerator process stays clean).

`REF_BASELINE` (360 img/s) is an adopted comparison anchor: a strong
per-V100 fp32 ResNet-50 training throughput for the cuDNN-era stack the
north star names (the reference itself publishes no numbers —
BASELINE.md). `vs_baseline` = measured / anchor.

Synthetic data everywhere (the reference's own benchmark pattern:
`datasets/iterator/impl/BenchmarkDataSetIterator.java`) so ETL is
excluded, matching how `PerformanceListener.java:87-88` isolates
compute.
"""

import json
import os
import subprocess
import sys
import time

import numpy as np

REF_BASELINE = 360.0  # img/s — adopted anchor (see module docstring)

# bf16 peak TFLOP/s by device-kind substring (public TPU specs).
_PEAK_TFLOPS = [
    ("v6", 918.0), ("trillium", 918.0), ("v5p", 459.0), ("v5e", 197.0),
    ("v5 lite", 197.0), ("v4", 275.0), ("v3", 123.0), ("v2", 45.0),
]
_DEFAULT_TPU_PEAK = 197.0  # unknown TPU-class part: assume v5e


def _device_info():
    import jax
    d = jax.devices()[0]
    plat = getattr(d, "platform", "cpu")
    kind = str(getattr(d, "device_kind", plat)).lower()
    accel = plat != "cpu"
    peak = None
    if accel:
        peak = _DEFAULT_TPU_PEAK
        for key, val in _PEAK_TFLOPS:
            if key in kind:
                peak = val
                break
    return plat, kind, accel, peak


# --------------------------------------------------------------- ResNet-50
def bench_resnet50(accel):
    import jax
    import jax.numpy as jnp
    from deeplearning4j_tpu.zoo.resnet50 import ResNet50

    batch = 64 if accel else 8
    size = 224 if accel else 64
    steps = 20 if accel else 3

    model = ResNet50(num_classes=1000, height=size, width=size, channels=3)
    if accel:
        # fp32 params, bf16 compute — convs hit the MXU at full rate
        from deeplearning4j_tpu.nd.dtype import bf16_policy
        from deeplearning4j_tpu.nn.graph import ComputationGraph
        net = ComputationGraph(model.conf(), dtype_policy=bf16_policy()).init(model.seed)
    else:
        net = model.init()

    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.standard_normal((batch, size, size, 3)),
                    jnp.bfloat16 if accel else jnp.float32)
    y = jnp.asarray(np.eye(1000, dtype=np.float32)[rng.integers(0, 1000, batch)])

    step = net._make_train_step()

    # AOT-compile once; reuse the same executable for cost_analysis AND
    # the timed loop (jit dispatch would otherwise re-trace/compile —
    # ResNet-50 compiles are minutes on a real chip, don't pay twice).
    # The iteration counter must be a traced arg (not a Python int that
    # would respecialize), so pin it as a jnp scalar.
    hlo_flops = None
    try:
        it0 = jnp.asarray(0, jnp.int32)
        compiled = step.lower(net.params, net.updater_state, net.net_state,
                              it0, [x], [y], jax.random.PRNGKey(0),
                              None, None).compile()
        cost = compiled.cost_analysis()
        if isinstance(cost, (list, tuple)):
            cost = cost[0]
        f = float(cost.get("flops", 0.0))
        hlo_flops = f if f > 0 else None

        def run(step_args, it):
            params, upd, state = step_args
            out = compiled(params, upd, state, jnp.asarray(it, jnp.int32),
                           [x], [y], jax.random.PRNGKey(it), None, None)
            return (out[0], out[1], out[2]), out[3]
    except Exception:
        def run(step_args, it):
            params, upd, state = step_args
            out = step(params, upd, state, it, [x], [y],
                       jax.random.PRNGKey(it), None, None)
            return (out[0], out[1], out[2]), out[3]
    # analytic: ResNet-50 fwd ≈ 4.1 GFLOP/img at 224² (conv-dominated,
    # scales with spatial area); train step ≈ 3x fwd (fwd + 2x in bwd)
    analytic_flops = 3.0 * 4.1e9 * (size / 224.0) ** 2 * batch

    st = (net.params, net.updater_state, net.net_state)
    st, loss = run(st, 0)            # warmup / compile
    jax.block_until_ready(loss)

    losses = []
    t0 = time.perf_counter()
    for i in range(1, steps + 1):
        st, loss = run(st, i)
        losses.append(loss)
    jax.block_until_ready(losses[-1])
    dt = time.perf_counter() - t0

    losses = [float(l) for l in losses]
    ips = batch * steps / dt
    flops_per_step = hlo_flops if hlo_flops else analytic_flops
    achieved_tflops = flops_per_step * steps / dt / 1e12
    plat, kind, _, peak = _device_info()
    mfu = (achieved_tflops / peak) if peak else None
    return {
        "metric": "resnet50_train_images_per_sec_per_chip",
        "value": round(ips, 2),
        "unit": "images/sec",
        "vs_baseline": round(ips / REF_BASELINE, 3),
        "platform": plat,
        "device_kind": kind,
        "batch": batch, "image_size": size, "steps": steps,
        "seconds": round(dt, 4),
        "flops_per_step_hlo": hlo_flops,
        "flops_per_step_analytic": round(analytic_flops),
        "achieved_tflops": round(achieved_tflops, 2),
        "peak_bf16_tflops": peak,
        "mfu": round(mfu, 4) if mfu is not None else None,
        "mfu_plausible": (mfu is None or mfu <= 1.0),
        "loss_first": losses[0], "loss_last": losses[-1],
        "train_signal_ok": losses[-1] < losses[0],
    }


def _time_mln_steps(net, x, y, steps):
    """Warm up + time `steps` jitted train steps on a MultiLayerNetwork.
    Returns elapsed seconds (compile excluded)."""
    import jax

    step = net._make_train_step(tbptt=False)
    st = (net.params, net.updater_state, net.net_state)

    def run(st, it):
        out = step(st[0], st[1], st[2], it, x, y, jax.random.PRNGKey(it),
                   None, None, None)
        return (out[0], out[1], out[2]), out[3]

    st, loss = run(st, 0)
    jax.block_until_ready(loss)
    t0 = time.perf_counter()
    for i in range(1, steps + 1):
        st, loss = run(st, i)
    jax.block_until_ready(loss)
    return time.perf_counter() - t0


# ------------------------------------------------------- LeNet (config 0)
def bench_lenet(accel):
    import jax.numpy as jnp
    from deeplearning4j_tpu.zoo.lenet import LeNet

    batch = 128 if accel else 64
    steps = 30 if accel else 5
    net = LeNet(num_classes=10).init()
    rng = np.random.default_rng(1)
    x = jnp.asarray(rng.standard_normal((batch, 28, 28, 1)), jnp.float32)
    y = jnp.asarray(np.eye(10, dtype=np.float32)[rng.integers(0, 10, batch)])
    dt = _time_mln_steps(net, x, y, steps)
    ips = batch * steps / dt
    return {
        "metric": "lenet_mnist_images_per_sec", "value": round(ips, 2),
        "unit": "images/sec", "batch": batch, "steps": steps,
        "epoch_seconds_60k": round(60000.0 / ips, 3),
    }


# --------------------------------------------- LSTM char-RNN (config 2)
def bench_lstm_charnn(accel):
    import jax.numpy as jnp
    from deeplearning4j_tpu.zoo.textgenlstm import TextGenerationLSTM

    vocab, T = 77, 100
    batch = 64 if accel else 8
    steps = 20 if accel else 3
    net = TextGenerationLSTM(vocab_size=vocab).init()
    rng = np.random.default_rng(2)
    ids = rng.integers(0, vocab, (batch, T))
    x = jnp.asarray(np.eye(vocab, dtype=np.float32)[ids])
    y = jnp.asarray(np.eye(vocab, dtype=np.float32)[np.roll(ids, -1, axis=1)])
    dt = _time_mln_steps(net, x, y, steps)
    return {
        "metric": "lstm_charnn_chars_per_sec",
        "value": round(batch * T * steps / dt, 1), "unit": "chars/sec",
        "batch": batch, "seq_len": T, "steps": steps,
    }


# --------------------------------------------------- Word2Vec (config 3)
def bench_word2vec(accel):
    from deeplearning4j_tpu.nlp.word2vec import Word2Vec

    rng = np.random.default_rng(3)
    vocab, n_sent, sent_len = 5000, (200 if accel else 40), 250
    # zipf-ish corpus so the vocab/negative-table paths do real work
    probs = 1.0 / np.arange(1, vocab + 1)
    probs /= probs.sum()
    seqs = [[f"w{t}" for t in rng.choice(vocab, sent_len, p=probs)]
            for _ in range(n_sent)]
    total_words = n_sent * sent_len

    w2v = Word2Vec(layer_size=128, window_size=5, negative_sample=5,
                   min_word_frequency=1, epochs=1, batch_size=4096)
    w2v.build_vocab(seqs)
    t0 = time.perf_counter()
    w2v.fit(seqs)
    dt = time.perf_counter() - t0
    return {
        "metric": "word2vec_skipgram_words_per_sec",
        "value": round(total_words / dt, 1), "unit": "words/sec",
        "corpus_words": total_words, "vector_length": 128,
    }


# --------------------------------- multi-device scaling (config 4)
def bench_scaling_subprocess():
    """Scaling shape on an 8-virtual-device CPU mesh, in a subprocess so
    this process's accelerator backend is untouched."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = (env.get("XLA_FLAGS", "")
                        + " --xla_force_host_platform_device_count=8").strip()
    proc = subprocess.run([sys.executable, os.path.abspath(__file__),
                           "--scaling-child"],
                          capture_output=True, text=True, timeout=1200,
                          env=env, cwd=os.path.dirname(os.path.abspath(__file__)))
    if proc.returncode != 0:
        return {"error": (proc.stderr or proc.stdout)[-500:]}
    return json.loads(proc.stdout.strip().splitlines()[-1])


def _scaling_child():
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh

    from deeplearning4j_tpu.common.updaters import Adam
    from deeplearning4j_tpu.common.weights import WeightInit
    from deeplearning4j_tpu.nn.conf import InputType, NeuralNetConfiguration
    from deeplearning4j_tpu.nn.layers import (
        ConvolutionLayer, DenseLayer, OutputLayer, SubsamplingLayer,
    )
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
    from deeplearning4j_tpu.parallel.trainer import ParallelTrainer

    def build():
        conf = (NeuralNetConfiguration.builder()
                .seed(7).updater(Adam(1e-3)).weight_init(WeightInit.XAVIER)
                .list()
                .layer(ConvolutionLayer(n_out=16, kernel_size=(3, 3),
                                        activation="relu"))
                .layer(SubsamplingLayer(kernel_size=(2, 2), stride=(2, 2)))
                .layer(DenseLayer(n_out=64, activation="relu"))
                .layer(OutputLayer(n_out=10, activation="softmax",
                                   loss="mcxent"))
                .set_input_type(InputType.convolutional(28, 28, 1))
                .build())
        return MultiLayerNetwork(conf).init()

    rng = np.random.default_rng(0)
    per_dev = 64
    out = {}
    for mode in ("sync", "averaging"):
        ips_by_n = {}
        for n in (1, 2, 4, 8):
            devs = np.array(jax.devices()[:n])
            mesh = Mesh(devs, ("data",))
            model = build()
            B = per_dev * n
            x = rng.standard_normal((B, 28, 28, 1)).astype(np.float32)
            y = np.eye(10, dtype=np.float32)[rng.integers(0, 10, B)]
            tr = ParallelTrainer(model, mesh, mode=mode,
                                 averaging_frequency=1)
            tr.fit(x, y, epochs=1, batch_size=B)      # warmup/compile
            steps = 5
            t0 = time.perf_counter()
            tr.fit(x, y, epochs=steps, batch_size=B)
            dt = time.perf_counter() - t0
            ips_by_n[str(n)] = round(B * steps / dt, 1)
        eff = ips_by_n["8"] / (8.0 * ips_by_n["1"]) if ips_by_n["1"] else None
        out[mode] = {"images_per_sec_by_devices": ips_by_n,
                     "scaling_efficiency_8x": round(eff, 3) if eff else None}
    print(json.dumps({"metric": "dataparallel_scaling_cpu8", **out}))


def main():
    plat, kind, accel, _ = _device_info()
    primary = bench_resnet50(accel)

    extras = {}
    for name, fn in (("lenet_mnist", bench_lenet),
                     ("lstm_char_rnn", bench_lstm_charnn),
                     ("word2vec", bench_word2vec)):
        try:
            extras[name] = fn(accel)
        except Exception as e:  # secondary metric must not kill the run
            extras[name] = {"error": f"{type(e).__name__}: {e}"[:300]}
    try:
        extras["scaling_cpu8"] = bench_scaling_subprocess()
    except Exception as e:
        extras["scaling_cpu8"] = {"error": f"{type(e).__name__}: {e}"[:300]}

    primary["extras"] = extras
    print(json.dumps(primary))


if __name__ == "__main__":
    if "--scaling-child" in sys.argv:
        _scaling_child()
    else:
        main()
